"""Fault-tolerant RPC transport for the parameter-server runtime.

Capability mirror of the reference's PS transport
(operators/distributed/rpc_client.h, rpc_server.h, grpc/ + brpc/
implementations, send_recv.proto.in): a length-prefixed binary protocol
over TCP sockets carrying numpy tensors. The reference serialises
through protobuf + zero-copy bytebuffers over gRPC/BRPC; here the framing
is a 32-byte header (method id, dtype, ndim, aux, client id, sequence
number) + shape + raw array bytes — no pickle of untrusted data,
payloads are raw tensor buffers.

Failure is a first-class condition (the reference leans on gRPC's retry
env knobs + heart_beat_monitor.h; Li et al. OSDI'14 build retry into the
PS transport itself):

* every call carries a (client id, per-client monotonic seq) pair; the
  server remembers the last (seq, reply) per client, so a retried frame
  — e.g. a send_grad whose reply was lost — is answered from the cache
  instead of re-applied: exactly-once application under retries;
* RPCClient.call reconnects on ConnectionError/OSError and retries with
  exponential backoff + jitter under a per-call deadline
  (FLAGS_ps_rpc_timeout / FLAGS_ps_rpc_max_retries /
  FLAGS_ps_rpc_backoff), raising errors.RpcDeadlineError /
  errors.RpcError when the budget is gone, and evicting itself from the
  shared pool so the next get() starts from a fresh connection. The
  schedule itself (backoff curve, jitter, deadline-first decision) is
  the shared core/retry.py RetryPolicy — this transport contributes the
  sockets, the typed errors and the ps.rpc_* counter names;
* named fault-injection sites (core/faults.py): `ps.rpc.send` before a
  request frame leaves, `ps.rpc.recv` before the reply is read,
  `ps.handler` around server-side dispatch — a seeded PT_FAULT_SPEC
  drives deterministic chaos through the exact production code paths;
* telemetry: ps.rpc_retries / ps.rpc_reconnects /
  ps.rpc_deadline_exceeded / ps.rpc_dedup_hits alongside the existing
  call/bytes/latency accounting.

Server: a thread-per-connection loop dispatching to a handler object
(finished threads are reaped; shutdown closes live connections and joins
with a bounded wait). Client: one pooled connection per endpoint,
thread-safe via a lock, reconnecting under the hood.
"""

from __future__ import annotations

import itertools
import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ...core import faults, telemetry, trace
from ...core import flags as _flags
from ...core import retry as _retry
from ...core.analysis import lockdep
from ..errors import RpcDeadlineError, RpcError, RpcRemoteError

# trace-context separator on the wire: when a sampled trace is active the
# client appends "\x1f<trace>-<span>" to the frame's method string, so the
# context survives retries byte-identically (same frame, same seq) and the
# server's dedup replay path never re-dispatches — one logical client span,
# at most one handler span per applied request
_TRACE_SEP = "\x1f"

# method_len, name_len, dtype_code, ndim, aux, client_id, seq
_HDR = struct.Struct("<IIHHIQQ")
_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "bool",
           "float16", "bfloat16"]
_MAX_FRAME = 1 << 33  # 8 GiB: generous tensor cap, rejects garbage lengths
_MAX_NDIM = 32


def _send_msg(sock, method: str, name: str, arr: Optional[np.ndarray],
              aux: int = 0, client: int = 0, seq: int = 0):
    mb = method.encode()
    nb = name.encode()
    if arr is None:
        head = _HDR.pack(len(mb), len(nb), 0xFFFF, 0, aux, client, seq)
        body = b""
        shape = b""
    else:
        arr = np.ascontiguousarray(arr)
        code = _DTYPES.index(str(arr.dtype))
        head = _HDR.pack(len(mb), len(nb), code, arr.ndim, aux, client, seq)
        shape = struct.pack(f"<{arr.ndim}q", *arr.shape)
        body = arr.tobytes()
    payload = head + mb + nb + shape + body
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock) -> Tuple[str, str, Optional[np.ndarray], int, int, int]:
    """Decode one frame. Every header field is validated against the
    payload before any allocation/frombuffer — a malformed or truncated
    frame raises ConnectionError (connection-fatal, never mis-frames the
    next message) instead of IndexError deep in numpy."""
    (total,) = struct.unpack("<Q", _recv_exact(sock, 8))
    if total < _HDR.size or total > _MAX_FRAME:
        raise ConnectionError(f"malformed RPC frame: length {total}")
    payload = _recv_exact(sock, total)
    mlen, nlen, code, ndim, aux, client, seq = _HDR.unpack_from(payload, 0)
    off = _HDR.size
    if off + mlen + nlen > total or ndim > _MAX_NDIM:
        raise ConnectionError(
            f"malformed RPC frame: header (mlen={mlen} nlen={nlen} "
            f"ndim={ndim}) exceeds payload of {total}")
    method = payload[off:off + mlen].decode(); off += mlen
    name = payload[off:off + nlen].decode(); off += nlen
    if code == 0xFFFF:
        if off != total:
            raise ConnectionError("malformed RPC frame: trailing bytes "
                                  "on tensor-less message")
        return method, name, None, aux, client, seq
    if code >= len(_DTYPES) or off + 8 * ndim > total:
        raise ConnectionError(
            f"malformed RPC frame: dtype code {code} / shape overrun")
    shape = struct.unpack_from(f"<{ndim}q", payload, off)
    off += 8 * ndim
    if any(d < 0 for d in shape):
        raise ConnectionError(f"malformed RPC frame: negative dim {shape}")
    dt = np.dtype(_DTYPES[code])
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if off + count * dt.itemsize != total:
        raise ConnectionError(
            f"malformed RPC frame: {total - off} body bytes for shape "
            f"{shape} {dt}")
    arr = np.frombuffer(payload, dtype=dt, offset=off, count=count)
    return method, name, arr.reshape(shape).copy(), aux, client, seq


class RPCServer:
    """reference: operators/distributed/rpc_server.h RPCServer +
    request_handler_impl.cc — handler(method, name, array, aux) ->
    (array|None, aux)."""

    def __init__(self, endpoint: str, handler: Callable):
        host, port = endpoint.rsplit(":", 1)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(64)
        self.endpoint = f"{host}:{self._srv.getsockname()[1]}"
        self._handler = handler
        self._stop = threading.Event()
        self._threads = []
        self._conns = set()
        self._conns_lock = lockdep.lock("rpc.server.conns")
        # retry dedup: client_id -> (last seq, reply | None=in-flight).
        # The client serialises its calls, so one entry per client makes
        # a resent frame (reply lost in transit) answerable without
        # re-dispatching — exactly-once application for send_grad/kv_push.
        # A retry that lands while the original is STILL dispatching (the
        # client gave up on the reply early) waits on the condition for
        # the in-flight reply instead of racing a second apply.
        self._dedup: Dict[int, Tuple[int, Optional[tuple]]] = {}
        self._dedup_cv = lockdep.condition("rpc.server.dedup")
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="pt-ps-rpc-accept",
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="pt-ps-rpc-conn", daemon=True)
            t.start()
            # reap finished connection threads so a long-lived server
            # with churning clients doesn't grow the list without bound;
            # the list is rebound here AND in shutdown() (another
            # thread), so both writers take the conns lock
            with self._conns_lock:
                self._threads.append(t)
                if len(self._threads) > 32:
                    self._threads = [th for th in self._threads
                                     if th.is_alive()]

    def _dedup_claim(self, client: int, seq: int) -> Optional[tuple]:
        """Returns the cached reply to replay for a duplicate frame, or
        None after claiming (seq, in-flight) — the caller must then
        dispatch and publish the reply. A duplicate of an in-flight
        original blocks here until the original publishes (or its
        connection thread dies and releases the claim)."""
        with self._dedup_cv:
            while True:
                entry = self._dedup.get(client)
                if entry is None or entry[0] != seq:
                    self._dedup[client] = (seq, None)   # claim
                    return None
                if entry[1] is not None:
                    return entry[1]
                # original still dispatching — wait for its reply
                if not self._dedup_cv.wait(timeout=30.0):
                    # wedged original: reclaim rather than hang the retry
                    self._dedup[client] = (seq, None)
                    return None

    def _dispatch(self, method, name, arr, aux) -> tuple:
        """Run the handler behind the `ps.handler` fault site. An
        injected ConnectionError/OSError drops the connection (the
        client retries); any other exception — injected or real — is
        relayed to the caller as an '__err__' status."""
        try:
            faults.maybe_fail("ps.handler", method=method)
        except (ConnectionError, OSError):
            raise
        except Exception as e:
            return ("__err__", f"{type(e).__name__}: {e}", None, 0)
        try:
            out, oaux = self._handler(method, name, arr, aux)
        except Exception as e:  # surface to the caller, keep serving
            return ("__err__", f"{type(e).__name__}: {e}", None, 0)
        return ("ok", name, out, oaux)

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                method, name, arr, aux, client, seq = _recv_msg(conn)
                # strip the propagated trace context (if any) BEFORE any
                # method comparison/dispatch — the wire method is
                # "<method>[\x1f<trace>-<span>]"
                method, _, tparent = method.partition(_TRACE_SEP)
                if method == "__stop__":
                    _send_msg(conn, "ok", "", None, client=client, seq=seq)
                    self._stop.set()
                    try:
                        self._srv.close()
                    except OSError:
                        pass
                    return
                if client and seq:
                    replay = self._dedup_claim(client, seq)
                    if replay is not None:
                        # a retry of the last frame: the original was
                        # applied but its reply was lost — answer from
                        # the cache, do NOT re-dispatch
                        telemetry.counter_add("ps.rpc_dedup_hits", 1,
                                              method=method)
                        _send_msg(conn, *replay, client=client, seq=seq)
                        continue
                try:
                    if tparent:
                        # continue the client's trace: one handler span per
                        # actually-dispatched request (replays above never
                        # reach here)
                        with trace.span_from(tparent, "ps.rpc.handler",
                                             method=method):
                            reply = self._dispatch(method, name, arr, aux)
                    else:
                        reply = self._dispatch(method, name, arr, aux)
                except BaseException:
                    # dispatch died without a reply (injected connection
                    # fault): release the in-flight claim so the retry
                    # re-dispatches instead of waiting forever
                    if client and seq:
                        with self._dedup_cv:
                            if self._dedup.get(client) == (seq, None):
                                del self._dedup[client]
                            self._dedup_cv.notify_all()
                    raise
                if client and seq:
                    # publish before the send: a reply lost on the wire
                    # must still be replayable to the retry
                    with self._dedup_cv:
                        self._dedup[client] = (seq, reply)
                        self._dedup_cv.notify_all()
                _send_msg(conn, *reply, client=client, seq=seq)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def wait(self):
        while not self._stop.is_set():
            self._stop.wait(0.2)

    def shutdown(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        # unblock connection threads stuck in recv, then join (bounded:
        # daemon threads may not exit if a handler is wedged)
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        deadline = time.monotonic() + 2.0
        with self._conns_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._conns_lock:
            self._threads = [t for t in self._threads if t.is_alive()]


class RPCClient:
    """reference: operators/distributed/rpc_client.h (AsyncSendVar /
    AsyncGetVar surface, synchronous under the hood here) + the gRPC
    client's retry knobs, made explicit: call() reconnects and retries
    under a deadline instead of dying with its socket."""

    _pool: Dict[str, "RPCClient"] = {}
    _pool_lock = lockdep.lock("rpc.client.pool")
    _ids = itertools.count(1)

    def __init__(self, endpoint: str, timeout: Optional[float] = None):
        """timeout: socket/connect timeout when no per-call deadline is
        active (FLAGS_ps_rpc_timeout <= 0); None uses blocking sockets.
        Connection is LAZY — a client constructed while its server is
        down connects on the first call."""
        self.endpoint = endpoint
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        # held for the WHOLE retry schedule of one call: this client's
        # calls are serialised by design (one socket, one in-flight seq)
        self._lock = lockdep.lock("rpc.client.call")
        self._was_connected = False
        # (client id, per-call seq) ride the frame header for server-side
        # retry dedup; pid + process counter keeps ids unique across the
        # trainer fleet without coordination
        self._client_id = ((os.getpid() & 0xFFFFFFFF) << 32) | \
            (next(RPCClient._ids) & 0xFFFFFFFF)
        self._seq = 0

    @classmethod
    def get(cls, endpoint: str) -> "RPCClient":
        with cls._pool_lock:
            cli = cls._pool.get(endpoint)
            if cli is None:
                cli = cls(endpoint)
                cls._pool[endpoint] = cli
            return cli

    @classmethod
    def reset_pool(cls):
        with cls._pool_lock:
            for cli in cls._pool.values():
                cli._close()
            cls._pool.clear()

    def evict(self):
        """Drop this client's socket and remove it from the shared pool
        so the next get() builds a fresh client instead of a corpse."""
        self._close()
        with RPCClient._pool_lock:
            if RPCClient._pool.get(self.endpoint) is self:
                del RPCClient._pool[self.endpoint]

    # -- connection plumbing -------------------------------------------------
    def _close(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _connect(self, sched: "_retry.RetrySchedule"):
        host, port = self.endpoint.rsplit(":", 1)
        self._sock = socket.create_connection(
            (host, int(port)),
            timeout=sched.remaining(default=self._timeout))
        if self._was_connected:
            telemetry.counter_add("ps.rpc_reconnects", 1,
                                  endpoint=self.endpoint)
        self._was_connected = True

    # -- the call ------------------------------------------------------------
    def call(self, method: str, name: str = "", arr=None, aux: int = 0,
             deadline: Optional[float] = None,
             max_retries: Optional[int] = None):
        """One request/reply exchange with retry/backoff/deadline.

        deadline: seconds of total budget for this call (default
        FLAGS_ps_rpc_timeout; <= 0 means unbounded). max_retries:
        reconnect-and-resend attempts (default FLAGS_ps_rpc_max_retries).
        Retries resend the SAME sequence number, so a request that was
        applied before its reply was lost is answered from the server's
        dedup cache instead of being re-applied."""
        a = None if arr is None else np.asarray(arr)
        budget = _flags.flag("ps_rpc_timeout") if deadline is None \
            else float(deadline)
        retries = _flags.flag("ps_rpc_max_retries") if max_retries is None \
            else int(max_retries)
        backoff = _flags.flag("ps_rpc_backoff")
        t0 = time.perf_counter()
        policy = _retry.RetryPolicy(
            max_retries=retries, backoff=backoff,
            deadline=budget if budget and budget > 0 else None)
        # the span covers the WHOLE retry schedule — retries resend the
        # same frame (same seq, same propagated context), so client call
        # and server handler stay one logical parent/child pair no matter
        # how many wire attempts it took
        with trace.span("ps.rpc.call", method=method,
                        endpoint=self.endpoint) as tctx:
            wire_method = method if tctx is None \
                else method + _TRACE_SEP + tctx.header()
            with self._lock:
                self._seq += 1
                seq = self._seq
                sched = policy.start()
                while True:
                    try:
                        faults.maybe_fail("ps.rpc.send", method=method,
                                          endpoint=self.endpoint)
                        if self._sock is None:
                            # pt-lint: disable=blocking-call-under-lock(one socket per client: calls serialise on the lock by design, bounded by the retry schedule's deadline)
                            self._connect(sched)
                        self._sock.settimeout(
                            sched.remaining(default=self._timeout))
                        # pt-lint: disable=blocking-call-under-lock(serialised per-client protocol; the socket timeout bounds the send)
                        _send_msg(self._sock, wire_method, name, a, aux,
                                  self._client_id, seq)
                        faults.maybe_fail("ps.rpc.recv", method=method,
                                          endpoint=self.endpoint)
                        status, err, out, oaux, _, rseq = \
                            _recv_msg(self._sock)  # pt-lint: disable=blocking-call-under-lock(reply read is the call; settimeout() above bounds it to the deadline)
                        if rseq and rseq != seq:
                            raise ConnectionError(
                                f"out-of-sequence reply: got {rseq}, "
                                f"expected {seq}")
                        break
                    except (ConnectionError, OSError) as e:
                        self._close()
                        outcome, delay = sched.note_failure()
                        if outcome == _retry.DEADLINE:
                            telemetry.counter_add(
                                "ps.rpc_deadline_exceeded", 1,
                                method=method)
                            self.evict()
                            raise RpcDeadlineError(
                                f"PS RPC '{method}' to {self.endpoint} "
                                f"exceeded its {budget:.3f}s deadline "
                                f"(attempt {sched.attempt}: "
                                f"{type(e).__name__}: {e})") from e
                        if outcome == _retry.EXHAUSTED:
                            self.evict()
                            raise RpcError(
                                f"PS RPC '{method}' to {self.endpoint} "
                                f"failed after {sched.attempt} attempts: "
                                f"{type(e).__name__}: {e}") from e
                        telemetry.counter_add("ps.rpc_retries", 1,
                                              method=method)
                        time.sleep(delay)  # pt-lint: disable=blocking-call-under-lock(retry backoff: concurrent callers of this client must wait out the schedule anyway; delay is deadline-clipped)
            # transport accounting (reference analog: the gRPC/BRPC client
            # metrics) — call count, payload bytes each way, latency
            # histogram
            telemetry.counter_add("ps.rpc_calls", 1, method=method)
            if a is not None:
                telemetry.counter_add("ps.rpc_send_bytes", int(a.nbytes))
            if out is not None:
                telemetry.counter_add("ps.rpc_recv_bytes", int(out.nbytes))
            telemetry.observe("ps.rpc_ms", (time.perf_counter() - t0) * 1e3,
                              kind="timer", method=method)
            if status == "__err__":
                telemetry.counter_add("ps.rpc_errors", 1, method=method)
                rtype = err.split(":", 1)[0] if ":" in err else ""
                raise RpcRemoteError(
                    f"PS RPC '{method}' failed on {self.endpoint}: {err}",
                    remote_type=rtype)
            return out, oaux

    def stop_server(self):
        try:
            # a short, retry-free budget: stopping an already-dead server
            # must not burn the full retry/deadline schedule
            self.call("__stop__", deadline=5.0, max_retries=0)
        except (RpcError, ConnectionError, OSError):
            pass


def start_heartbeat(endpoints, trainer_id: int, interval: float = 10.0,
                    metrics_url: str = ""):
    """Trainer-side liveness pings (reference: the trainer's periodic
    beat consumed by heart_beat_monitor.h). A daemon thread pings every
    pserver on its own connection so a trainer blocked in a sync recv
    still reads as alive. Returns a stop() callable; stop also closes
    the private sockets (under the same lock the beat thread holds while
    using them, so a close can't race a call in flight).

    ``metrics_url`` (the trainer's telemetry.start_metrics_server URL,
    when it runs one) rides the beat's spare ``name`` field: the pserver
    lands it in core/fleetobs.announce, so a fleet aggregator colocated
    with the PS tier scrapes trainers with zero extra RPCs."""
    if isinstance(endpoints, str):
        endpoints = [e.strip() for e in endpoints.split(",") if e.strip()]
    stop = threading.Event()
    clients: Dict[str, Optional[RPCClient]] = {ep: None for ep in endpoints}
    clients_lock = lockdep.lock("rpc.heartbeat.clients")

    def beat():
        # connect lazily + reconnect after any failure: a pserver that is
        # not up yet (launch race) or restarts mid-run must not silence
        # heartbeats forever. One attempt per tick — the beat itself is
        # the retry loop (call-level retries would pile up behind a dead
        # server and skew the beat period).
        while not stop.wait(interval):
            for ep in endpoints:
                with clients_lock:
                    if stop.is_set():
                        return
                    try:
                        if clients[ep] is None:
                            clients[ep] = RPCClient(ep, timeout=interval)
                        clients[ep].call("heartbeat", name=metrics_url,
                                         aux=int(trainer_id),
                                         deadline=interval, max_retries=0)
                    except (RpcError, ConnectionError, OSError):
                        cli, clients[ep] = clients[ep], None
                        if cli is not None:
                            cli._close()

    threading.Thread(target=beat, name="pt-ps-heartbeat",
                     daemon=True).start()

    def stop_heartbeat():
        stop.set()
        with clients_lock:
            for ep, cli in clients.items():
                if cli is not None:
                    cli._close()
                clients[ep] = None

    return stop_heartbeat
