"""DistributeTranspiler — split a training program into trainer + pserver
programs.

Capability mirror of the reference's
python/paddle/fluid/transpiler/distribute_transpiler.py:256 (transpile)
and :545 (program splitting): optimizer-role ops move to parameter
servers, the trainer keeps forward/backward and gains send(grad) /
recv(param) ops, params are assigned to pservers balanced by size.

Differences from the reference, by design:
* whole-param placement by DEFAULT; `transpile(slice_var_up=True)`
  enables the reference's block-splitting (one block per pserver along
  dim 0, per-block accumulators, grad split / param concat on the
  trainer) — and the large-sparse path is the sharded LargeScaleKV
  service (kv_service.py) rather than sliced dense tables;
* trainer and pserver initialise from the SAME deterministic startup
  program (same seeds), so no startup-time parameter broadcast is
  needed;
* the update runs through the framework's own interpreting executor on
  the pserver (pserver.py), so optimizer semantics match local training
  exactly.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core.ir import OpDesc, OpRole, Program


def _op_role(op: OpDesc) -> int:
    return int(op.attrs.get("op_role", 0))


def _is_server_side(op: OpDesc) -> bool:
    """Optimizer ops AND lr-schedule ops move to the pserver (reference
    moves lr decay there too — distribute_transpiler.py)."""
    r = _op_role(op)
    return bool(r & int(OpRole.Optimize)) or bool(r & int(OpRole.LRSched))


class DistributeTranspiler:
    """reference: transpiler/distribute_transpiler.py DistributeTranspiler."""

    def __init__(self, config=None):
        self.config = config
        self._done = False

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  startup_program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: bool = True, slice_var_up: bool = False,
                  min_block_size: int = 8192):
        """slice_var_up=True splits every large parameter into one block
        per pserver along dim 0 (reference distribute_transpiler.py:545
        slice_variable) — no single server holds a whole giant tensor.
        Each block becomes an independent (param, grad) pair: the trainer
        splits the grad before send and concats the blocks after recv;
        block accumulators are created per block; the block's INITIAL
        value is sliced from the full deterministic init, so sliced
        training matches whole-param (and local) training exactly."""
        from ...core.ir import default_main_program, default_startup_program

        self.trainer_id = int(trainer_id)
        self.program = program or default_main_program()
        self.startup = startup_program or default_startup_program()
        self.endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        self.trainers = int(trainers)
        self.sync_mode = bool(sync_mode)

        block = self.program.global_block()
        # -- collect optimizer-role ops and their (param, grad) pairs -------
        opt_ops = [op for op in block.ops if _is_server_side(op)]
        pairs: List[Tuple[str, str]] = []      # (param, grad) in op order
        for op in opt_ops:
            p = op.inputs.get("Param")
            g = op.inputs.get("Grad")
            if p and g and (p[0], g[0]) not in pairs:
                pairs.append((p[0], g[0]))
        if not pairs:
            raise ValueError(
                "transpile: program has no optimizer ops (append them via "
                "optimizer.minimize before transpiling)")
        grad_names = {g for _, g in pairs}

        # per-grad op groups: every Optimize op that reads or writes the
        # grad (regularizer/clip scale ops included); ops touching no grad
        # (lr schedules, counters) are replicated to every pserver
        self.grad_to_ops: Dict[str, List[OpDesc]] = {g: [] for g in grad_names}
        common_ops: List[OpDesc] = []
        for op in opt_ops:
            touched = [n for n in list(op.input_names())
                       + list(op.output_names()) if n in grad_names]
            if touched:
                self.grad_to_ops[touched[0]].append(op)
            else:
                common_ops.append(op)

        # -- optional: slice big params into per-pserver blocks -------------
        # self._sliced: param -> {"sections", "p_blocks", "g_blocks"}
        self._sliced: Dict[str, dict] = {}
        if slice_var_up and len(self.endpoints) > 1:
            pairs = self._slice_vars(block, pairs, int(min_block_size))

        # -- assign params to pservers, balanced by parameter size ----------
        def size_of(name):
            v = block.var(name)
            n = 1
            for d in (v.shape or ()):
                n *= max(int(d), 1)
            return n

        order = sorted(pairs, key=lambda pg: -size_of(pg[0]))
        load = [0] * len(self.endpoints)
        self.param_to_ep: Dict[str, str] = {}
        self.grad_to_param: Dict[str, str] = {}
        # sliced blocks pin block k to endpoint k (the point of slicing);
        # whole params balance greedily over the remaining load
        for info in self._sliced.values():
            for k, (pb, gb) in enumerate(zip(info["p_blocks"],
                                             info["g_blocks"])):
                ep_i = k % len(self.endpoints)
                self.param_to_ep[pb] = self.endpoints[ep_i]
                self.grad_to_param[gb] = pb
                load[ep_i] += size_of(pb)
        for p, g in order:
            if p in self.param_to_ep:
                continue
            i = int(np.argmin(load))
            self.param_to_ep[p] = self.endpoints[i]
            self.grad_to_param[g] = p
            load[i] += size_of(p)
        self._pairs = pairs
        self._common_ops = common_ops
        self._done = True
        return self

    def _slice_vars(self, block, pairs, min_block_size):
        """Split each big param's (param, grad) pair and optimizer op
        group into per-block versions (reference slice_variable +
        _create_vars_from_blocklist)."""
        n_eps = len(self.endpoints)
        new_pairs: List[Tuple[str, str]] = []
        # block var -> (full var, row start, row end); rows None = scalar
        self._block_src: Dict[str, tuple] = {}

        def bvar(name, shape, dtype, **kw):
            # create_var silently returns an existing var: re-transpiling
            # the same program with a different pserver count would reuse
            # stale-shaped blocks — fail loudly instead
            if block.has_var(name) and \
                    list(block.var(name).shape or ()) != list(shape):
                raise ValueError(
                    f"slice_var_up: block var '{name}' already exists "
                    f"with shape {block.var(name).shape}, new slicing "
                    f"wants {shape} — transpile a fresh program (or the "
                    f"same pserver count)")
            return block.create_var(name=name, shape=shape, dtype=dtype,
                                    **kw)
        for p, g in pairs:
            pv = block.var(p)
            shape = list(pv.shape or ())
            rows = int(shape[0]) if shape else 0
            numel = int(np.prod([max(int(d), 1) for d in shape])) if shape \
                else 0
            if rows < n_eps or numel < min_block_size * n_eps:
                new_pairs.append((p, g))
                continue
            base, rem = divmod(rows, n_eps)
            sections = [base + (1 if k < rem else 0) for k in range(n_eps)]
            starts = list(np.cumsum([0] + sections[:-1]))
            p_blocks, g_blocks = [], []
            ops = self.grad_to_ops.pop(g)
            for k, rk in enumerate(sections):
                bshape = [rk] + shape[1:]
                pb, gb = f"{p}.block{k}", f"{g}.block{k}"
                bvar(pb, bshape, pv.dtype, persistable=True)
                bvar(gb, bshape, pv.dtype, stop_gradient=True)
                self._block_src[pb] = (p, int(starts[k]),
                                       int(starts[k]) + rk)
                p_blocks.append(pb)
                g_blocks.append(gb)
                blk_ops = []
                for op in ops:
                    nop = OpDesc(op.type, dict(op.inputs),
                                 dict(op.outputs), dict(op.attrs))
                    writes = set(op.output_names())
                    rename = {p: pb, g: gb}
                    # param-shaped aux state (moments/velocity) slices
                    # with the param; [1]-shaped state (beta pows)
                    # replicates per block under a block-suffixed name
                    for name in list(nop.input_names()) \
                            + list(nop.output_names()):
                        if name in rename or name in (p, g):
                            continue
                        v = block._find_var_recursive(name)
                        if v is None or not getattr(v, "persistable", False):
                            continue
                        vshape = list(v.shape or ())
                        if vshape and int(vshape[0]) == rows:
                            nb = f"{name}.block{k}"
                            bvar(nb, [rk] + vshape[1:], v.dtype,
                                 persistable=True)
                            rename[name] = nb
                            self._block_src[nb] = (name, int(starts[k]),
                                                   int(starts[k]) + rk)
                        elif vshape == [1] and name in writes:
                            # read-WRITE scalar state (beta pows)
                            # replicates per block; input-only scalars
                            # (the shared LR var, whatever its name)
                            # stay shared so LR schedules keep working
                            nb = f"{name}.block{k}"
                            bvar(nb, [1], v.dtype, persistable=True)
                            rename[name] = nb
                            self._block_src[nb] = (name, None, None)
                    for slot, names in nop.inputs.items():
                        nop.inputs[slot] = [rename.get(n, n) for n in names]
                    for slot, names in nop.outputs.items():
                        nop.outputs[slot] = [rename.get(n, n)
                                             for n in names]
                    blk_ops.append(nop)
                self.grad_to_ops[gb] = blk_ops
                new_pairs.append((pb, gb))
            self._sliced[p] = {"sections": sections, "grad": g,
                               "p_blocks": p_blocks, "g_blocks": g_blocks}
        return new_pairs

    # -- trainer side --------------------------------------------------------
    def get_trainer_program(self) -> Program:
        """Forward + backward, optimizer ops replaced by send/recv; for
        sliced params the grad SPLITS before the sends and the received
        blocks CONCAT back (reference: the splited-var send/concat the
        transpiler emits around grad/param blocks)."""
        assert self._done, "call transpile() first"
        trainer = Program()
        dst = trainer.global_block()
        dst._load_dict(self.program.global_block().to_dict())
        dst.ops = [op for op in dst.ops if not _is_server_side(op)]
        role = {"op_role": int(OpRole.Optimize)}
        for info in self._sliced.values():
            dst.ops.append(OpDesc(
                "split", {"X": [info["grad"]]},
                {"Out": list(info["g_blocks"])},
                {"sections": list(info["sections"]), "axis": 0, **role}))
        # send each grad to its param's pserver, then recv updated params
        for p, g in self._pairs:
            ep = self.param_to_ep[p]
            dst.ops.append(OpDesc(
                "send", {"X": [g]}, {},
                {"endpoint": ep, "trainer_id": self.trainer_id,
                 "var_names": [g], "sync_mode": self.sync_mode, **role}))
        dst.ops.append(OpDesc("send_barrier", {}, {}, {
            "endpoints": list(self.endpoints), **role}))
        for p, g in self._pairs:
            ep = self.param_to_ep[p]
            dst.ops.append(OpDesc(
                "recv", {}, {"Out": [p]},
                {"endpoint": ep, "trainer_id": self.trainer_id,
                 "var_names": [p], "sync_mode": self.sync_mode, **role}))
        for full, info in self._sliced.items():
            dst.ops.append(OpDesc(
                "concat", {"X": list(info["p_blocks"])}, {"Out": [full]},
                {"axis": 0, **role}))
        dst.ops.append(OpDesc("fetch_barrier", {}, {}, {
            "endpoints": list(self.endpoints), **role}))
        trainer._bump_version()
        return trainer

    # -- pserver side --------------------------------------------------------
    def get_pserver_programs(self, endpoint: str):
        """(pserver_program, pserver_startup) for one endpoint; also
        returns this endpoint's grad_to_param / grad_to_ops maps via
        attributes on the program for PServer construction."""
        assert self._done, "call transpile() first"
        my_params = {p for p, ep in self.param_to_ep.items()
                     if ep == endpoint}
        my_grads = {g for g, p in self.grad_to_param.items()
                    if p in my_params}
        src_block = self.program.global_block()

        prog = Program()
        blk = prog.global_block()
        my_ops: Dict[str, List[OpDesc]] = {}
        needed_vars = set()
        # common (LR-schedule/counter) ops are kept SEPARATE from the
        # per-grad groups: the PServer runs them once per global step,
        # not once per parameter apply
        common = list(self._common_ops) if my_grads else []
        for g in my_grads:
            my_ops[g] = list(self.grad_to_ops[g])
            for op in my_ops[g]:
                needed_vars.update(op.input_names())
                needed_vars.update(op.output_names())
        for op in common:
            needed_vars.update(op.input_names())
            needed_vars.update(op.output_names())
        needed_vars.discard("@EMPTY@")
        for name in sorted(needed_vars):
            if src_block.has_var(name):
                v = src_block.var(name)
                blk._load_dict({"vars": [v.desc.to_dict()], "ops": []})
        blk.ops.extend(common)
        for g in sorted(my_grads):
            blk.ops.extend(my_ops[g])
        prog._bump_version()

        # startup: original startup ops that produce the needed vars;
        # sliced-block vars initialise by running the FULL var's original
        # init then slicing the block out — bit-identical to the
        # whole-param (and local) initialisation, whatever the
        # initializer (reference keeps init on the pserver side too)
        block_src = getattr(self, "_block_src", {})
        full_needed = set()
        for name in needed_vars:
            if name in block_src:
                full_needed.add(block_src[name][0])
        startup = Program()
        sblk = startup.global_block()
        src_startup = self.startup.global_block()
        for name in sorted(needed_vars | full_needed):
            if src_startup.has_var(name):
                d = src_startup.var(name).desc.to_dict()
                if name in full_needed and name not in needed_vars:
                    # init-then-slice scratch: non-persistable, so the
                    # interpreting startup run DISCARDS the full tensor —
                    # a pserver must not retain whole sliced params
                    d = dict(d, persistable=False)
                sblk._load_dict({"vars": [d], "ops": []})
        for op in src_startup.ops:
            if any(o in needed_vars or o in full_needed
                   for o in op.output_names()):
                sblk.ops.append(op)
        for name in sorted(needed_vars):
            src = block_src.get(name)
            if src is None:
                continue
            # declare the block var in the startup block (persistable —
            # the interpreting run only writes DECLARED persistables back
            # to the scope) using the main-block descriptor
            if not sblk.has_var(name) and src_block.has_var(name):
                sblk._load_dict(
                    {"vars": [src_block.var(name).desc.to_dict()],
                     "ops": []})
            full, s0, s1 = src
            if s0 is None:               # [1]-shaped replica (beta pows)
                sblk.ops.append(OpDesc("assign", {"X": [full]},
                                       {"Out": [name]}, {}))
            else:
                sblk.ops.append(OpDesc(
                    "slice", {"Input": [full]}, {"Out": [name]},
                    {"axes": [0], "starts": [s0], "ends": [s1]}))
        startup._bump_version()

        prog._ps_grad_to_param = {g: self.grad_to_param[g]
                                  for g in my_grads}
        prog._ps_grad_to_ops = my_ops
        prog._ps_common_ops = common
        return prog, startup

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Trainer startup is the original startup (deterministic seeds
        make trainer and pserver initial params identical)."""
        return self.startup
