"""DistributeTranspiler — split a training program into trainer + pserver
programs.

Capability mirror of the reference's
python/paddle/fluid/transpiler/distribute_transpiler.py:256 (transpile)
and :545 (program splitting): optimizer-role ops move to parameter
servers, the trainer keeps forward/backward and gains send(grad) /
recv(param) ops, params are assigned to pservers balanced by size.

Differences from the reference, by design:
* whole-param placement (no block-splitting of one tensor across
  pservers — the reference slices large tensors; here the large-sparse
  path is the LargeScaleKV service instead);
* trainer and pserver initialise from the SAME deterministic startup
  program (same seeds), so no startup-time parameter broadcast is
  needed;
* the update runs through the framework's own interpreting executor on
  the pserver (pserver.py), so optimizer semantics match local training
  exactly.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core.ir import OpDesc, OpRole, Program


def _op_role(op: OpDesc) -> int:
    return int(op.attrs.get("op_role", 0))


def _is_server_side(op: OpDesc) -> bool:
    """Optimizer ops AND lr-schedule ops move to the pserver (reference
    moves lr decay there too — distribute_transpiler.py)."""
    r = _op_role(op)
    return bool(r & int(OpRole.Optimize)) or bool(r & int(OpRole.LRSched))


class DistributeTranspiler:
    """reference: transpiler/distribute_transpiler.py DistributeTranspiler."""

    def __init__(self, config=None):
        self.config = config
        self._done = False

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  startup_program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: bool = True):
        from ...core.ir import default_main_program, default_startup_program

        self.trainer_id = int(trainer_id)
        self.program = program or default_main_program()
        self.startup = startup_program or default_startup_program()
        self.endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        self.trainers = int(trainers)
        self.sync_mode = bool(sync_mode)

        block = self.program.global_block()
        # -- collect optimizer-role ops and their (param, grad) pairs -------
        opt_ops = [op for op in block.ops if _is_server_side(op)]
        pairs: List[Tuple[str, str]] = []      # (param, grad) in op order
        for op in opt_ops:
            p = op.inputs.get("Param")
            g = op.inputs.get("Grad")
            if p and g and (p[0], g[0]) not in pairs:
                pairs.append((p[0], g[0]))
        if not pairs:
            raise ValueError(
                "transpile: program has no optimizer ops (append them via "
                "optimizer.minimize before transpiling)")
        grad_names = {g for _, g in pairs}

        # per-grad op groups: every Optimize op that reads or writes the
        # grad (regularizer/clip scale ops included); ops touching no grad
        # (lr schedules, counters) are replicated to every pserver
        self.grad_to_ops: Dict[str, List[OpDesc]] = {g: [] for g in grad_names}
        common_ops: List[OpDesc] = []
        for op in opt_ops:
            touched = [n for n in list(op.input_names())
                       + list(op.output_names()) if n in grad_names]
            if touched:
                self.grad_to_ops[touched[0]].append(op)
            else:
                common_ops.append(op)

        # -- assign params to pservers, balanced by parameter size ----------
        def size_of(name):
            v = block.var(name)
            n = 1
            for d in (v.shape or ()):
                n *= max(int(d), 1)
            return n

        order = sorted(pairs, key=lambda pg: -size_of(pg[0]))
        load = [0] * len(self.endpoints)
        self.param_to_ep: Dict[str, str] = {}
        self.grad_to_param: Dict[str, str] = {}
        for p, g in order:
            i = int(np.argmin(load))
            self.param_to_ep[p] = self.endpoints[i]
            self.grad_to_param[g] = p
            load[i] += size_of(p)
        self._pairs = pairs
        self._common_ops = common_ops
        self._done = True
        return self

    # -- trainer side --------------------------------------------------------
    def get_trainer_program(self) -> Program:
        """Forward + backward, optimizer ops replaced by send/recv."""
        assert self._done, "call transpile() first"
        trainer = Program()
        dst = trainer.global_block()
        dst._load_dict(self.program.global_block().to_dict())
        dst.ops = [op for op in dst.ops if not _is_server_side(op)]
        # send each grad to its param's pserver, then recv updated params
        for p, g in self._pairs:
            ep = self.param_to_ep[p]
            dst.ops.append(OpDesc(
                "send", {"X": [g]}, {},
                {"endpoint": ep, "trainer_id": self.trainer_id,
                 "var_names": [g], "sync_mode": self.sync_mode,
                 "op_role": int(OpRole.Optimize)}))
        dst.ops.append(OpDesc("send_barrier", {}, {}, {
            "endpoints": list(self.endpoints),
            "op_role": int(OpRole.Optimize)}))
        for p, g in self._pairs:
            ep = self.param_to_ep[p]
            dst.ops.append(OpDesc(
                "recv", {}, {"Out": [p]},
                {"endpoint": ep, "var_names": [p],
                 "sync_mode": self.sync_mode,
                 "op_role": int(OpRole.Optimize)}))
        dst.ops.append(OpDesc("fetch_barrier", {}, {}, {
            "endpoints": list(self.endpoints),
            "op_role": int(OpRole.Optimize)}))
        trainer._bump_version()
        return trainer

    # -- pserver side --------------------------------------------------------
    def get_pserver_programs(self, endpoint: str):
        """(pserver_program, pserver_startup) for one endpoint; also
        returns this endpoint's grad_to_param / grad_to_ops maps via
        attributes on the program for PServer construction."""
        assert self._done, "call transpile() first"
        my_params = {p for p, ep in self.param_to_ep.items()
                     if ep == endpoint}
        my_grads = {g for g, p in self.grad_to_param.items()
                    if p in my_params}
        src_block = self.program.global_block()

        prog = Program()
        blk = prog.global_block()
        my_ops: Dict[str, List[OpDesc]] = {}
        needed_vars = set()
        # common (LR-schedule/counter) ops are kept SEPARATE from the
        # per-grad groups: the PServer runs them once per global step,
        # not once per parameter apply
        common = list(self._common_ops) if my_grads else []
        for g in my_grads:
            my_ops[g] = list(self.grad_to_ops[g])
            for op in my_ops[g]:
                needed_vars.update(op.input_names())
                needed_vars.update(op.output_names())
        for op in common:
            needed_vars.update(op.input_names())
            needed_vars.update(op.output_names())
        needed_vars.discard("@EMPTY@")
        for name in sorted(needed_vars):
            if src_block.has_var(name):
                v = src_block.var(name)
                blk._load_dict({"vars": [v.desc.to_dict()], "ops": []})
        blk.ops.extend(common)
        for g in sorted(my_grads):
            blk.ops.extend(my_ops[g])
        prog._bump_version()

        # startup: original startup ops that produce the needed vars
        startup = Program()
        sblk = startup.global_block()
        src_startup = self.startup.global_block()
        for name in sorted(needed_vars):
            if src_startup.has_var(name):
                sblk._load_dict(
                    {"vars": [src_startup.var(name).desc.to_dict()],
                     "ops": []})
        for op in src_startup.ops:
            if any(o in needed_vars for o in op.output_names()):
                sblk.ops.append(op)
        startup._bump_version()

        prog._ps_grad_to_param = {g: self.grad_to_param[g]
                                  for g in my_grads}
        prog._ps_grad_to_ops = my_ops
        prog._ps_common_ops = common
        return prog, startup

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Trainer startup is the original startup (deterministic seeds
        make trainer and pserver initial params identical)."""
        return self.startup
