"""Distributed Python API (reference: python/paddle/distributed/).

fleet orchestration, collective user API, launch CLI, parallel env init —
over jax.distributed + mesh sharding instead of NCCL/gRPC stacks.
"""

from . import errors  # noqa: F401
from . import fleet  # noqa: F401
from .collective import (ReduceOp, all_gather, all_reduce, barrier,  # noqa: F401
                         broadcast, get_rank, get_world_size, reduce, scatter)
from .parallel import (ParallelEnv, init_parallel_env,  # noqa: F401
                       spawn)
