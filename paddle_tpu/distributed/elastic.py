"""Elastic training: checkpoint-restart failure recovery.

Capability mirror of the reference's failure-detection story (SURVEY.md
§5): the reference has a pserver-side HeartBeatMonitor
(operators/distributed/heart_beat_monitor.h:51) and a placeholder
`DistributedStrategy.elastic` flag but NO in-tree trainer recovery —
"checkpoint-restart based recovery is the realistic TPU equivalent".
This module provides that equivalent: a supervised step loop that
checkpoints periodically and, when a step raises a recoverable error,
restores the newest checkpoint and resumes, up to max_restarts.

    runner = ElasticRunner(ckpt_dir, program, scope,
                           save_interval_steps=10)
    runner.run(step_fn, num_steps)   # step_fn(step) -> loss

On a multi-host job the same script re-launched by the cluster manager
lands in restore_latest() and continues — the reference's
checkpoint_notify flow without the pserver middleman.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Tuple

_LOG = logging.getLogger("paddle_tpu.elastic")

# error types worth a restart (device resets, transient RPC failures);
# programming errors (TypeError, ValueError, ...) re-raise immediately
RECOVERABLE = (RuntimeError, ConnectionError, OSError, TimeoutError)


class ElasticRunner:
    def __init__(self, ckpt_dir: str, program=None, scope=None,
                 save_interval_steps: int = 10, max_to_keep: int = 3,
                 max_restarts: int = 3,
                 recoverable: Tuple[type, ...] = RECOVERABLE):
        from ..checkpoint import CheckpointManager

        self.program = program
        self.scope = scope
        self.max_restarts = int(max_restarts)
        self.recoverable = tuple(recoverable)
        self.save_interval = int(save_interval_steps)
        self.mgr = CheckpointManager(ckpt_dir, max_to_keep=max_to_keep,
                                     save_interval_steps=save_interval_steps)
        self.restarts = 0

    def run(self, step_fn: Callable[[int], object], num_steps: int,
            on_restart: Optional[Callable[[int, BaseException], None]] = None):
        """Run step_fn(step) for num_steps with failure recovery.

        Returns the last step_fn result. Restores from the newest
        checkpoint on a recoverable exception; re-raises after
        max_restarts (or immediately for non-recoverable types)."""
        step = self.mgr.restore_latest(self.program, self.scope)
        if step:
            _LOG.info("elastic: resumed from checkpoint step %d", step)
        else:
            # baseline checkpoint of the INITIAL weights: a failure before
            # the first periodic save must restore to step 0's state, not
            # keep the partially-trained scope and re-run from step 0
            try:
                self.mgr.save(0, self.program, self.scope)
                # the manager saves ASYNC by default; the baseline must be
                # durable before any step can fail and need it
                self.mgr.wait_until_finished()
            except ValueError:
                pass     # nothing persistable yet -> nothing to restore
        result = None
        while step < num_steps:
            try:
                result = step_fn(step)
            except self.recoverable as e:
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    _LOG.error("elastic: step %d failed after %d restarts",
                               step, self.max_restarts)
                    raise
                restored = self.mgr.restore_latest(self.program, self.scope)
                _LOG.warning(
                    "elastic: step %d raised %r — restart %d/%d from "
                    "checkpoint step %d", step, e, self.restarts,
                    self.max_restarts, restored)
                if on_restart is not None:
                    on_restart(step, e)
                step = restored
                continue
            step += 1
            self.mgr.save(step, self.program, self.scope)
        self.mgr.wait_until_finished()
        return result
