"""Elastic training: checkpoint-restart failure recovery.

Capability mirror of the reference's failure-detection story (SURVEY.md
§5): the reference has a pserver-side HeartBeatMonitor
(operators/distributed/heart_beat_monitor.h:51) and a placeholder
`DistributedStrategy.elastic` flag but NO in-tree trainer recovery —
"checkpoint-restart based recovery is the realistic TPU equivalent".
This module provides that equivalent: a supervised step loop that
checkpoints periodically and, when a step raises a recoverable error,
restores the newest VERIFIED checkpoint and resumes, up to max_restarts.

    runner = ElasticRunner(ckpt_dir, program, scope,
                           save_interval_steps=10)
    runner.run(step_fn, num_steps)   # step_fn(step) -> loss

Exact resume: each checkpoint carries the global RNG state (restored by
the manager) and, when a `reader` with ``state_dict()``/``set_state()``
is attached (the double-buffer _GeneratorLoader grew that surface), the
reader cursor — a restored run re-reads exactly the batch that was in
flight when the step failed. The step loop runs under try/finally
``wait_until_finished()`` so teardown can't truncate an in-flight async
save; checkpoint-save failures (e.g. injected ``ckpt.save.*`` faults)
are themselves recoverable, not fatal.

On a multi-host job the same script re-launched by the cluster manager
lands in restore_latest() and continues — the reference's
checkpoint_notify flow without the pserver middleman.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Tuple

from .errors import RpcError

_LOG = logging.getLogger("paddle_tpu.elastic")

# error types worth a restart: transport failures (RpcError covers
# RpcDeadlineError/RpcRemoteError — retries exhausted, deadlines blown,
# barrier stalls relayed from a pserver) and the OS-level network/device
# errors underneath them. Plain RuntimeError is deliberately NOT here —
# it swallowed programming errors; raise one of these (or subclass) from
# custom step_fns that want a restart. In particular core.verify's
# ProgramVerifyError (a RuntimeError) names a corrupt PROGRAM: restoring
# a checkpoint and re-running the same program would fail identically
# forever, so it must re-raise (tests/test_verify.py pins this).
RECOVERABLE = (RpcError, ConnectionError, OSError, TimeoutError)


class ElasticRunner:
    def __init__(self, ckpt_dir: str, program=None, scope=None,
                 save_interval_steps: int = 10, max_to_keep: int = 3,
                 max_restarts: int = 3,
                 recoverable: Tuple[type, ...] = RECOVERABLE,
                 reader=None, async_save: bool = True):
        from ..checkpoint import CheckpointManager

        self.program = program
        self.scope = scope
        self.max_restarts = int(max_restarts)
        self.recoverable = tuple(recoverable)
        self.save_interval = int(save_interval_steps)
        self.reader = reader
        self.mgr = CheckpointManager(ckpt_dir, max_to_keep=max_to_keep,
                                     save_interval_steps=save_interval_steps,
                                     async_save=async_save)
        self.restarts = 0

    def _recoverable_exc(self, e: BaseException) -> bool:
        """True if e — or anything on its explicit cause chain — is a
        recoverable type. The interpreting executor wraps op failures in
        ExecutionError `from` the original, so a transport RpcError
        surfacing through a send/recv op still counts; a wrapped
        TypeError still re-raises."""
        seen = set()
        while e is not None and id(e) not in seen:
            if isinstance(e, self.recoverable):
                return True
            seen.add(id(e))
            e = e.__cause__
        return False

    # -- exact-resume extras -------------------------------------------------
    def _extras(self) -> dict:
        ex = {}
        if self.reader is not None and hasattr(self.reader, "state_dict"):
            ex["reader"] = self.reader.state_dict()
        return ex

    def _apply_restored_extras(self):
        ex = self.mgr.last_restore_extras
        if self.reader is not None and hasattr(self.reader, "set_state") \
                and "reader" in ex:
            self.reader.set_state(ex["reader"])

    def _save_baseline(self):
        """Baseline checkpoint of the INITIAL weights: a failure before
        the first periodic save must restore to step 0's state, not keep
        the partially-trained scope and re-run from step 0. Saved
        synchronously (durable before any step can fail and need it),
        with one retry against injected/transient save faults."""
        for attempt in (1, 2):
            try:
                self.mgr.save(0, self.program, self.scope,
                              extras=self._extras(), force=True)
                self.mgr.wait_until_finished()
                return
            except ValueError:
                return   # nothing persistable yet -> nothing to restore
            except self.recoverable as e:
                _LOG.warning("elastic: baseline checkpoint attempt %d "
                             "failed: %r", attempt, e)

    def run(self, step_fn: Callable[[int], object], num_steps: int,
            on_restart: Optional[Callable[[int, BaseException], None]] = None):
        """Run step_fn(step) for num_steps with failure recovery.

        Returns the last step_fn result. Restores from the newest
        verified checkpoint on a recoverable exception (from the step OR
        from the checkpoint save itself); re-raises after max_restarts
        (or immediately for non-recoverable types)."""
        step = self.mgr.restore_latest(self.program, self.scope)
        if step:
            self._apply_restored_extras()
            _LOG.info("elastic: resumed from checkpoint step %d", step)
        else:
            self._save_baseline()
        result = None
        try:
            while step < num_steps:
                try:
                    result = step_fn(step)
                    step += 1
                    self.mgr.save(step, self.program, self.scope,
                                  extras=self._extras())
                except Exception as e:
                    if not self._recoverable_exc(e):
                        raise
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        _LOG.error("elastic: step %d failed after %d "
                                   "restarts", step, self.max_restarts)
                        raise
                    restored = self.mgr.restore_latest(self.program,
                                                       self.scope)
                    self._apply_restored_extras()
                    _LOG.warning(
                        "elastic: step %d raised %r — restart %d/%d from "
                        "checkpoint step %d", step, e, self.restarts,
                        self.max_restarts, restored)
                    if on_restart is not None:
                        on_restart(step, e)
                    step = restored
        finally:
            # teardown join: process exit must not truncate an in-flight
            # async save (the checkpoint module's atexit hook is the
            # last-resort backstop; this is the orderly path)
            self.mgr.wait_until_finished()
        return result

    def close(self):
        self.mgr.close()
