"""Elastic training: checkpoint-restart failure recovery + elastic resize.

Capability mirror of the reference's failure-detection story (SURVEY.md
§5): the reference has a pserver-side HeartBeatMonitor
(operators/distributed/heart_beat_monitor.h:51) and a placeholder
`DistributedStrategy.elastic` flag but NO in-tree trainer recovery —
"checkpoint-restart based recovery is the realistic TPU equivalent".
This module provides that equivalent: a supervised step loop that
checkpoints periodically and, when a step raises a recoverable error,
restores the newest VERIFIED checkpoint and resumes, up to max_restarts.

    runner = ElasticRunner(ckpt_dir, program, scope,
                           save_interval_steps=10)
    runner.run(step_fn, num_steps)   # step_fn(step) -> loss

Exact resume: each checkpoint carries the global RNG state (restored by
the manager) and, when a `reader` with ``state_dict()``/``set_state()``
is attached (the double-buffer _GeneratorLoader grew that surface), the
reader cursor — a restored run re-reads exactly the batch that was in
flight when the step failed. The step loop runs under try/finally
``wait_until_finished()`` so teardown can't truncate an in-flight async
save; checkpoint-save failures (e.g. injected ``ckpt.save.*`` faults)
are themselves recoverable, not fatal.

Restart budget: with ``FLAGS_elastic_restart_window_s`` > 0 only the
restarts inside that sliding window count against ``max_restarts`` —
sustained progress refunds the crash budget instead of a lifetime
counter bleeding it dry (``elastic.restart_budget_refunds``). Every
restart lands a ``kind:"scale"`` record in the incident ring
(core/incidents.report_scale_event).

Elastic resize: attach a ``scaler`` (distributed/scaler.ScalerPolicy)
and an ``on_scale`` callback and the runner executes ScaleUp/ScaleDown
decisions between steps as checkpoint → barrier-drain → relaunch-at-
new-world: the current step is force-checkpointed, the async writer is
drained, and ``on_scale(decision)`` rebuilds the world (program, scope,
step_fn, reader) at the target size — the runner then restores the
checkpoint INTO the new world (world-size-changing resume: dense arrays
re-lay out at the next compile, ZeRO state regroups via
parallel/zero_regroup, the reader cursor re-splits across the new
trainer set) and continues the step loop.

On a multi-host job the same script re-launched by the cluster manager
lands in restore_latest() and continues — the reference's
checkpoint_notify flow without the pserver middleman.
"""

from __future__ import annotations

import logging
import signal as _signal
import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

from ..core import flags as _flags
from ..core import telemetry
from .errors import RpcError

_LOG = logging.getLogger("paddle_tpu.elastic")


class RestartBudgetExhaustedError(RuntimeError):
    """The windowed restart budget is spent: ``used`` restarts landed
    inside ``window_s`` (or lifetime, with no window) against a budget
    of ``max_restarts``. A supervisor that sees this must STOP
    respawning — the failure is systematic, not transient."""

    def __init__(self, used: int, max_restarts: int, window_s: float,
                 last_error: str = ""):
        self.used = int(used)
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self.last_error = last_error
        window = f" inside {window_s:.0f}s" if window_s > 0 else ""
        detail = f" (last: {last_error})" if last_error else ""
        super().__init__(
            f"restart budget exhausted: {used} restarts{window} against "
            f"max_restarts={max_restarts}{detail}")


class RestartBudget:
    """Sliding-window crash budget, shared by ElasticRunner (in-process
    restore-restart) and the launch.py orchestrator (child respawn).
    With ``window_s`` <= 0 the budget is a lifetime counter; otherwise
    only restarts inside the window count — pruning expired entries IS
    the refund for sustained progress (reported to ``on_refund`` so
    each owner counts refunds on its own metric name)."""

    def __init__(self, max_restarts: int, window_s: float = 0.0,
                 on_refund: Optional[Callable[[int], None]] = None):
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self.on_refund = on_refund
        self.times: deque = deque()
        self.lifetime = 0

    def used(self, now: Optional[float] = None) -> int:
        if self.window_s <= 0:
            return self.lifetime
        if now is None:
            now = time.monotonic()
        cut = now - self.window_s
        refunded = 0
        while self.times and self.times[0] < cut:
            self.times.popleft()
            refunded += 1
        if refunded and self.on_refund is not None:
            self.on_refund(refunded)
        return len(self.times)

    def note(self, now: Optional[float] = None) -> int:
        """Charge one restart; returns the post-charge used count."""
        if now is None:
            now = time.monotonic()
        self.lifetime += 1
        self.times.append(now)
        return self.used(now)

    def exhausted(self, now: Optional[float] = None) -> bool:
        return self.used(now) > self.max_restarts

    def check(self, now: Optional[float] = None, last_error: str = ""):
        """Raise RestartBudgetExhaustedError when over budget."""
        used = self.used(now)
        if used > self.max_restarts:
            raise RestartBudgetExhaustedError(
                used, self.max_restarts, self.window_s,
                last_error=last_error)

# error types worth a restart: transport failures (RpcError covers
# RpcDeadlineError/RpcRemoteError — retries exhausted, deadlines blown,
# barrier stalls relayed from a pserver) and the OS-level network/device
# errors underneath them. Plain RuntimeError is deliberately NOT here —
# it swallowed programming errors; raise one of these (or subclass) from
# custom step_fns that want a restart. In particular core.verify's
# ProgramVerifyError (a RuntimeError) names a corrupt PROGRAM: restoring
# a checkpoint and re-running the same program would fail identically
# forever, so it must re-raise (tests/test_verify.py pins this).
RECOVERABLE = (RpcError, ConnectionError, OSError, TimeoutError)


class ElasticRunner:
    def __init__(self, ckpt_dir: str, program=None, scope=None,
                 save_interval_steps: int = 10, max_to_keep: int = 3,
                 max_restarts: int = 3,
                 recoverable: Tuple[type, ...] = RECOVERABLE,
                 reader=None, async_save: bool = True,
                 restart_window_s: Optional[float] = None,
                 world_size: int = 1, scaler=None,
                 on_scale: Optional[Callable] = None):
        from ..checkpoint import CheckpointManager

        self.program = program
        self.scope = scope
        self.max_restarts = int(max_restarts)
        self.recoverable = tuple(recoverable)
        self.save_interval = int(save_interval_steps)
        self.reader = reader
        self.mgr = CheckpointManager(ckpt_dir, max_to_keep=max_to_keep,
                                     save_interval_steps=save_interval_steps,
                                     async_save=async_save)
        self.restarts = 0              # lifetime total (observability)
        self.restart_window_s = float(
            _flags.flag("elastic_restart_window_s")
            if restart_window_s is None else restart_window_s)
        self._budget = RestartBudget(
            self.max_restarts, self.restart_window_s,
            on_refund=lambda n: telemetry.counter_add(
                "elastic.restart_budget_refunds", n))
        # alias, not a copy: tests (and budget_used) poke the deque
        self._restart_times = self._budget.times
        self.world_size = int(world_size)
        self.scaler = scaler
        self.on_scale = on_scale
        self.scale_events = 0
        # cooperative drain (orchestrator SIGTERM path): the loop
        # force-saves at the next step boundary, bound-joins the async
        # writer, and returns instead of raising
        self._drain = threading.Event()
        self.drained_at: Optional[int] = None

    def _recoverable_exc(self, e: BaseException) -> bool:
        """True if e — or anything on its explicit cause chain — is a
        recoverable type. The interpreting executor wraps op failures in
        ExecutionError `from` the original, so a transport RpcError
        surfacing through a send/recv op still counts; a wrapped
        TypeError still re-raises."""
        seen = set()
        while e is not None and id(e) not in seen:
            if isinstance(e, self.recoverable):
                return True
            seen.add(id(e))
            e = e.__cause__
        return False

    # -- windowed restart budget ---------------------------------------------
    def budget_used(self, now: Optional[float] = None) -> int:
        """Restarts currently charged against max_restarts: all of them
        (legacy) or only those inside FLAGS_elastic_restart_window_s —
        pruning expired entries IS the refund for sustained progress."""
        if self.restart_window_s <= 0:
            return self.restarts
        return self._budget.used(now)

    def _note_restart(self, step: int, exc: BaseException) -> int:
        """Count one restart against the budget; returns the charged
        count. Each restart is a scale-plane event: a kind:"scale"
        record lands in the incident ring."""
        from ..core import incidents

        now = time.monotonic()
        self.restarts += 1
        self._budget.note(now)
        telemetry.counter_add("elastic.restarts", 1, step=step,
                              exc=type(exc).__name__)
        incidents.report_scale_event(
            "elastic", "restart", self.world_size, self.world_size,
            reason=type(exc).__name__,
            attrs={"step": int(step), "restarts": self.restarts})
        return self.budget_used(now)

    # -- cooperative drain ---------------------------------------------------
    def request_drain(self):
        """Ask the step loop to stop at the NEXT step boundary: force-
        checkpoint, bound-join the async writer, return cleanly. Safe
        from signal handlers and other threads (one Event.set)."""
        self._drain.set()

    @property
    def draining(self) -> bool:
        return self._drain.is_set()

    def install_signal_handlers(self, signals=(_signal.SIGTERM,
                                               _signal.SIGINT)):
        """Wire SIGTERM/SIGINT to request_drain() — the orchestrator's
        graceful-stop contract for trainer children. Main thread only
        (signal.signal's own constraint). Returns self."""
        for sig in signals:
            _signal.signal(sig, lambda _s, _f: self.request_drain())
        return self

    def _execute_drain(self, step: int) -> bool:
        """Force-save and BOUND-join the async writer (FLAGS_elastic_
        drain_timeout_s): a SIGTERM'd trainer must make its checkpoint
        durable before the supervisor's kill-escalation deadline, and a
        wedged writer must not turn a drain into a hang. Returns True
        when the writer fully drained."""
        timeout = float(_flags.flag("elastic_drain_timeout_s"))
        try:
            self.mgr.save(step, self.program, self.scope,
                          extras=self._extras(), force=True)
        except self.recoverable as e:
            _LOG.warning("elastic: drain checkpoint at step %d failed: "
                         "%r", step, e)
        ok = self.mgr.wait_until_finished(timeout=timeout)
        if not ok:
            telemetry.counter_add("elastic.drain_timeouts", 1, step=step)
            _LOG.error("elastic: async writer still busy after %.1fs "
                       "drain timeout at step %d", timeout, step)
        telemetry.counter_add("elastic.drains", 1, step=step)
        self.drained_at = int(step)
        return ok

    # -- exact-resume extras -------------------------------------------------
    def _extras(self) -> dict:
        ex = {}
        if self.reader is not None and hasattr(self.reader, "state_dict"):
            ex["reader"] = self.reader.state_dict()
        if self.world_size > 1:
            ex["world"] = {"size": int(self.world_size)}
        return ex

    def _apply_restored_extras(self):
        ex = self.mgr.last_restore_extras
        if self.reader is not None and hasattr(self.reader, "set_state") \
                and "reader" in ex:
            self.reader.set_state(ex["reader"])

    def _save_baseline(self):
        """Baseline checkpoint of the INITIAL weights: a failure before
        the first periodic save must restore to step 0's state, not keep
        the partially-trained scope and re-run from step 0. Saved
        synchronously (durable before any step can fail and need it),
        with one retry against injected/transient save faults."""
        for attempt in (1, 2):
            try:
                self.mgr.save(0, self.program, self.scope,
                              extras=self._extras(), force=True)
                self.mgr.wait_until_finished()
                return
            except ValueError:
                return   # nothing persistable yet -> nothing to restore
            except self.recoverable as e:
                _LOG.warning("elastic: baseline checkpoint attempt %d "
                             "failed: %r", attempt, e)

    # -- scale-decision execution --------------------------------------------
    def _maybe_scale(self, step: int, step_fn):
        """Poll the policy; on a decision, execute checkpoint →
        barrier-drain → relaunch-at-new-world. Returns the (possibly
        replaced) step_fn."""
        if self.scaler is None or self.on_scale is None:
            return step_fn
        decision = self.scaler.decide(self.world_size)
        if decision is None:
            return step_fn
        return self.execute_scale(decision, step, step_fn)

    def execute_scale(self, decision, step: int, step_fn):
        """The scale-event protocol, in order:

        1. force-checkpoint the current step (the relaunch resumes here);
        2. barrier-drain: join the async writer so the checkpoint is
           durable before any part of the old world is torn down;
        3. ``on_scale(decision)`` rebuilds the world at decision.target —
           it returns None to veto, or a dict with any of
           ``step_fn`` / ``program`` / ``scope`` / ``reader`` /
           ``world_size`` replaced;
        4. restore the checkpoint INTO the new world (the world-size-
           changing resume) and emit the ``kind:"scale"`` ring record.
        """
        from ..core import incidents

        self.mgr.save(step, self.program, self.scope,
                      extras=self._extras(), force=True)
        self.mgr.wait_until_finished()          # the barrier-drain
        swapped = self.on_scale(decision)
        if swapped is None:
            _LOG.warning("elastic: on_scale vetoed %s -> %d",
                         decision.direction, decision.target)
            return step_fn
        old_world = self.world_size
        self.program = swapped.get("program", self.program)
        self.scope = swapped.get("scope", self.scope)
        self.reader = swapped.get("reader", self.reader)
        self.world_size = int(swapped.get("world_size", decision.target))
        step_fn = swapped.get("step_fn", step_fn)
        restored = self.mgr.restore_latest(self.program, self.scope)
        self._apply_restored_extras()
        self.scale_events += 1
        telemetry.counter_add("elastic.scale_events", 1,
                              direction=decision.direction,
                              old_world=old_world,
                              new_world=self.world_size)
        incidents.report_scale_event(
            "elastic", "resize", old_world, self.world_size,
            reason=decision.reason,
            attrs={"step": int(restored),
                   "direction": decision.direction,
                   "signals": decision.signals})
        _LOG.info("elastic: resized world %d -> %d at step %d (%s)",
                  old_world, self.world_size, restored, decision.reason)
        return step_fn

    def run(self, step_fn: Callable[[int], object], num_steps: int,
            on_restart: Optional[Callable[[int, BaseException], None]] = None):
        """Run step_fn(step) for num_steps with failure recovery.

        Returns the last step_fn result. Restores from the newest
        verified checkpoint on a recoverable exception (from the step OR
        from the checkpoint save itself); re-raises after max_restarts
        (or immediately for non-recoverable types)."""
        step = self.mgr.restore_latest(self.program, self.scope)
        if step:
            self._apply_restored_extras()
            _LOG.info("elastic: resumed from checkpoint step %d", step)
        else:
            self._save_baseline()
        result = None
        try:
            while step < num_steps:
                if self._drain.is_set():
                    self._execute_drain(step)
                    break
                try:
                    result = step_fn(step)
                    step += 1
                    self.mgr.save(step, self.program, self.scope,
                                  extras=self._extras())
                    step_fn = self._maybe_scale(step, step_fn)
                except Exception as e:
                    if not self._recoverable_exc(e):
                        raise
                    used = self._note_restart(step, e)
                    if used > self.max_restarts:
                        _LOG.error("elastic: step %d failed after %d "
                                   "restarts%s", step, used,
                                   f" inside {self.restart_window_s:.0f}s"
                                   if self.restart_window_s > 0 else "")
                        raise
                    restored = self.mgr.restore_latest(self.program,
                                                       self.scope)
                    self._apply_restored_extras()
                    _LOG.warning(
                        "elastic: step %d raised %r — restart %d/%d from "
                        "checkpoint step %d", step, e, used,
                        self.max_restarts, restored)
                    if on_restart is not None:
                        on_restart(step, e)
                    step = restored
        finally:
            # teardown join: process exit must not truncate an in-flight
            # async save (the checkpoint module's atexit hook is the
            # last-resort backstop; this is the orderly path). A drain
            # already bound-joined; don't let a wedged writer hang the
            # drain exit unboundedly on top of that.
            if self._drain.is_set():
                self.mgr.wait_until_finished(
                    timeout=float(_flags.flag("elastic_drain_timeout_s")))
            else:
                self.mgr.wait_until_finished()
        return result

    def close(self):
        self.mgr.close()
