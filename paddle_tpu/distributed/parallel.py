"""Process-level distributed init.

Capability mirror of python/paddle/distributed/parallel.py:46
init_parallel_env (reference rendezvous: TCP store + NCCL comm bootstrap,
imperative/nccl_context.cc). TPU-native: jax.distributed.initialize against
the coordination service; env vars keep the reference's names
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS).
"""

from __future__ import annotations

import os

_initialized = False


def init_parallel_env() -> bool:
    """Initialise multi-host JAX if cluster env vars are present; no-op (and
    returns False) for single-host runs."""
    global _initialized
    if _initialized:
        return True
    import jax

    coord = os.environ.get("PADDLE_COORDINATOR_ADDR") or \
        os.environ.get("JAX_COORDINATOR_ADDRESS")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nprocs, process_id=pid)
        _initialized = True
        return True
    return False


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_world_size() -> int:
    import jax

    return jax.process_count()
