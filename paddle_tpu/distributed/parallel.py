"""Process-level distributed init.

Capability mirror of python/paddle/distributed/parallel.py:46
init_parallel_env (reference rendezvous: TCP store + NCCL comm bootstrap,
imperative/nccl_context.cc). TPU-native: jax.distributed.initialize against
the coordination service; env vars keep the reference's names
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS).
"""

from __future__ import annotations

import os

_initialized = False


def init_parallel_env() -> bool:
    """Initialise multi-host JAX if cluster env vars are present; no-op (and
    returns False) for single-host runs."""
    global _initialized
    if _initialized:
        return True
    import jax

    coord = os.environ.get("PADDLE_COORDINATOR_ADDR") or \
        os.environ.get("JAX_COORDINATOR_ADDRESS")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nprocs, process_id=pid)
        _initialized = True
        return True
    return False


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_world_size() -> int:
    import jax

    return jax.process_count()


class ParallelEnv:
    """Env-var accessor for the distributed context (reference:
    fluid/dygraph/parallel.py ParallelEnv — rank/world_size/endpoints
    from the PADDLE_* env the launcher sets)."""

    @property
    def rank(self) -> int:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    # reference alias
    local_rank = rank

    @property
    def world_size(self) -> int:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    nranks = world_size

    @property
    def device_id(self) -> int:
        # reference semantics: first entry of a possibly comma-separated
        # selected-devices list
        raw = os.environ.get("FLAGS_selected_gpus",
                             os.environ.get("PADDLE_LOCAL_DEVICE_ID", "0"))
        first = raw.split(",")[0].strip()
        return int(first) if first else 0

    @property
    def current_endpoint(self) -> str:
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


def cluster_env(rank, nprocs, coordinator):
    """Per-rank cluster env with the reference launcher's variable names
    (shared by spawn and the launch CLI so they cannot drift). Trainer
    endpoints are synthesized from the coordinator address — under
    jax.distributed the coordination service is the only real endpoint,
    but reference-ported code expects the list to be populated."""
    host, sep, port = coordinator.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"coordinator must be host:port, got {coordinator!r}")
    # synthesized ports are COSMETIC (nothing binds them; jax.distributed
    # uses only the coordinator) — keep them in the valid range so a
    # coordinator near 65535 with many ranks cannot produce port > 65535
    base = int(port)
    endpoints = [f"{host}:{(base + 1 + r - 1024) % 64511 + 1024}"
                 for r in range(nprocs)]
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_COORDINATOR_ADDR": coordinator,
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
    }


def _spawn_target(func, rank, nprocs, coordinator, env_overrides, args):
    os.environ.update(cluster_env(rank, nprocs, coordinator))
    os.environ.update(env_overrides)
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Launch `func` in nprocs fresh processes with the PADDLE_* cluster
    env set per rank (reference: distributed/spawn.py — there it
    assigns one GPU per process; here each process is one jax host
    joining the coordination service, so `func` typically starts with
    init_parallel_env()).

    Uses the 'spawn' start method: children must re-import jax cleanly —
    forking a process with an initialised backend deadlocks."""
    import multiprocessing as mp
    import socket

    if nprocs <= 0:
        env_n = os.environ.get("PADDLE_TRAINERS_NUM")
        if env_n:
            nprocs = int(env_n)
        else:
            # reference distributed/spawn.py defaults to all visible
            # devices; mirror that (ADVICE r4). The probe runs in a
            # SUBPROCESS: jax.local_device_count() in the launcher would
            # initialise the backend and take exclusive ownership of the
            # chips before any rank starts
            import subprocess
            import sys as _sys

            try:
                out = subprocess.run(
                    [_sys.executable, "-c",
                     "import jax; print(jax.local_device_count())"],
                    capture_output=True, timeout=60, text=True)
                nprocs = max(1, int(out.stdout.strip().splitlines()[-1]))
            except Exception:
                nprocs = 1
    coordinator = options.pop("coordinator", None)
    if coordinator is None:
        # probe-then-release has an inherent TOCTOU window (another
        # process can grab the port before rank 0's coordination
        # service binds it) — fine for a single launcher per host;
        # concurrent launchers should pass coordinator= explicitly
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    env_overrides = {str(k): str(v)
                     for k, v in options.pop("env", {}).items()}
    if options:
        raise TypeError(f"spawn: unknown options {sorted(options)}")

    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_spawn_target,
                        args=(func, rank, nprocs, coordinator,
                              env_overrides, tuple(args)),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    # first failure terminates the survivors (reference mp.spawn
    # semantics): a crashed rank leaves its peers blocked in the
    # collective rendezvous, so a plain sequential join would hang
    import time

    failed = []
    try:
        while True:
            alive = False
            for rank, p in enumerate(procs):
                if p.is_alive():
                    alive = True
                elif p.exitcode not in (0, None) and \
                        (rank, p.exitcode) not in failed:
                    failed.append((rank, p.exitcode))
            if failed or not alive:
                break
            time.sleep(0.1)
    finally:
        if failed:
            for p in procs:
                if p.is_alive():
                    p.terminate()
        for p in procs:
            p.join()
    if failed:
        raise RuntimeError(
            f"spawn: {len(failed)} of {nprocs} processes failed "
            f"(rank, exitcode): {failed}; surviving ranks terminated")
    return procs
