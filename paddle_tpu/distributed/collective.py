"""Eager collective user API on jax Arrays.

Capability mirror of python/paddle/distributed/collective.py (broadcast:59,
all_reduce:116, reduce:191, all_gather:274, scatter:347, barrier:419 — NCCL
ops under dygraph). Here the collectives run over the current mesh's 'dp'
axis via a tiny shard_map'd function per call; on a single device they are
identities (ring of size 1).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


def _mesh_axis(group=None):
    from ..parallel.mesh import get_mesh

    mesh = get_mesh()
    if mesh is None or "dp" not in mesh.shape or mesh.shape["dp"] <= 1:
        return None, None
    return mesh, "dp"


def _spmd(fn, mesh, axis, x, in_spec=None, out_spec=None):
    from jax.sharding import PartitionSpec as P

    from ..parallel.api import get_shard_map

    shard_map, kwargs = get_shard_map()
    return shard_map(fn, mesh=mesh, in_specs=in_spec or P(),
                     out_specs=out_spec or P(), **kwargs)(x)


def all_reduce(tensor, op: str = ReduceOp.SUM, group=None):
    import jax
    import jax.numpy as jnp

    mesh, axis = _mesh_axis(group)
    if mesh is None:
        return jnp.asarray(tensor)
    red = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
           "prod": lambda x, ax: jnp.prod(jax.lax.all_gather(x, ax), axis=0),
           }[op]
    return _spmd(lambda x: red(x, axis), mesh, axis, jnp.asarray(tensor))


def broadcast(tensor, src: int = 0, group=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh, axis = _mesh_axis(group)
    if mesh is None:
        return jnp.asarray(tensor)
    return _spmd(lambda x: jax.lax.all_gather(x, axis)[src], mesh, axis,
                 jnp.asarray(tensor))


def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group=None):
    return all_reduce(tensor, op, group)


def all_gather(tensor_list: Optional[List], tensor, group=None):
    """Returns the gathered [world, ...] array; also extends tensor_list for
    fluid API parity."""
    import jax
    import jax.numpy as jnp

    mesh, axis = _mesh_axis(group)
    if mesh is None:
        out = jnp.asarray(tensor)[None]
    else:
        out = _spmd(lambda x: jax.lax.all_gather(x, axis), mesh, axis,
                    jnp.asarray(tensor))
    if tensor_list is not None:
        tensor_list.extend(list(out))
    return out


def scatter(tensor, tensor_list=None, src: int = 0, group=None):
    import jax.numpy as jnp

    mesh, axis = _mesh_axis(group)
    if tensor_list is not None:
        stacked = jnp.stack([jnp.asarray(t) for t in tensor_list])
        if mesh is None:
            return stacked[0]
        import jax

        def body(x):
            return x[jax.lax.axis_index(axis)]

        return _spmd(body, mesh, axis, stacked)
    return jnp.asarray(tensor)


def barrier(group=None):
    """XLA programs are globally ordered; nothing to do single-controller."""
    return None


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_world_size() -> int:
    import jax

    return jax.process_count()
