"""Typed errors for the distributed/PS transport.

The reference distinguishes transport failures (gRPC status codes, the
retry env knobs GRPC_* consumed by grpc_client.cc) from application
errors surfaced by the remote handler. The seed collapsed everything
into RuntimeError, which forced ElasticRunner's RECOVERABLE tuple to
include plain RuntimeError — swallowing programming errors. This module
gives the transport its own hierarchy so recovery policy can be precise:

* RpcError            — transport-level failure after retries were
                        exhausted (reconnects kept failing). Recoverable.
* RpcDeadlineError    — the per-call deadline (FLAGS_ps_rpc_timeout)
                        elapsed before a reply arrived; also a
                        TimeoutError so pre-existing timeout handling
                        still matches. Recoverable.
* RpcRemoteError      — the remote handler raised and the error was
                        relayed over the wire (the '__err__' status).
                        Kept under RpcError because the dominant causes
                        (sync-barrier stalls, checkpoint races) are
                        transient cluster conditions, not local bugs.
* BarrierTimeoutError — raised pserver-side when a sync barrier stalls
                        past FLAGS_ps_sync_barrier_timeout; trainers see
                        it as an RpcRemoteError naming this type.
"""

from __future__ import annotations


class RpcError(RuntimeError):
    """PS transport failure (connect/send/recv kept failing)."""


class RpcDeadlineError(RpcError, TimeoutError):
    """Per-call deadline exceeded before a reply arrived."""


class RpcRemoteError(RpcError):
    """The remote handler raised; the error text travelled back as an
    '__err__' status frame. `.remote_type` holds the peer-side exception
    class name when it could be parsed."""

    def __init__(self, message: str, remote_type: str = ""):
        super().__init__(message)
        self.remote_type = remote_type


class BarrierTimeoutError(RuntimeError):
    """Sync barrier stalled past its timeout (pserver-side)."""
