"""DistributedStrategy — the composable training-strategy config.

Capability mirror of python/paddle/distributed/fleet/base/distributed_strategy.py
(protobuf-backed, framework/distributed_strategy.proto:106). Here a plain
serialisable object (save/load JSON replaces save_to_prototxt,
distributed_strategy.py:126). Each flag activates a meta-optimizer in
fleet.minimize's chain (meta_optimizers.py).
"""

from __future__ import annotations

import json
from typing import Any, Dict


class DistributedStrategy:
    def __init__(self):
        # mixed precision (reference :316)
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0, "use_dynamic_loss_scaling": False,
            "custom_white_list": [], "custom_black_list": [],
            "use_pure_bf16": False}
        # activation recompute (reference :381)
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        # pipeline parallelism (reference :615)
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "micro_batch_size": 1}
        # gradient merge / accumulation (reference :872)
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1, "avg": True}
        # large-batch optimizers (reference :929, :989)
        self.lars = False
        self.lars_configs: Dict[str, Any] = {"lars_coeff": 0.001,
                                             "lars_weight_decay": 0.0005}
        self.lamb = False
        self.lamb_configs: Dict[str, Any] = {"lamb_weight_decay": 0.01}
        # gradient compression (reference :808)
        self.dgc = False
        self.dgc_configs: Dict[str, Any] = {"rampup_begin_step": 0}
        # local sgd (reference localsgd_optimizer.py)
        self.localsgd = False
        self.localsgd_configs: Dict[str, Any] = {"k_steps": 1}
        # async PS (reference :235) — PS stack is host-KV in this build
        self.a_sync = False
        self.a_sync_configs: Dict[str, Any] = {}
        # collective topology (reference :421)
        self.hierarchical_allreduce = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        # tensor parallel (new first-class capability, SURVEY §2.7)
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {
            "tensor_parallel_degree": 1}
        # sharding/ZeRO-style optimizer-state partitioning (reference
        # :1026 sharding/sharding_configs; meta_optimizers.py
        # ShardingOptimizer): stage 1 shards optimizer state over dp,
        # stage 2 additionally reduce-scatters the gradients.
        # sharding_degree <= 1 means "use the full dp world"
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {"sharding_degree": 0,
                                                 "stage": 1}
        self.elastic = False
        self.auto = False

    # -- serialisation (reference: save_to_prototxt / load_from_prototxt) ----
    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def save_to_file(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, default=str)

    @staticmethod
    def load_from_file(path: str) -> "DistributedStrategy":
        s = DistributedStrategy()
        with open(path) as f:
            s.__dict__.update(json.load(f))
        return s

    def __repr__(self):
        on = [k for k, v in self.to_dict().items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
