"""Role makers — who am I in the cluster?

Capability mirror of python/paddle/distributed/fleet/base/role_maker.py
(PaddleCloudRoleMaker:33 parses PADDLE_* env; Gloo rendezvous :534). The
TPU-native rendezvous is jax.distributed's coordination service
(distributed/parallel.py); env var names are kept for launcher parity.
"""

from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def worker_num(self) -> int:
        raise NotImplementedError

    def worker_index(self) -> int:
        raise NotImplementedError

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective: bool = True, **kwargs):
        self._is_collective = is_collective
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "0"))
        self._worker_index = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")

    def _jax_world(self):
        try:
            import jax

            return jax.process_count(), jax.process_index()
        except Exception:
            return 1, 0

    def worker_num(self) -> int:
        if self._worker_num:
            return self._worker_num
        return self._jax_world()[0]

    def worker_index(self) -> int:
        if self._worker_num:
            return self._worker_index
        return self._jax_world()[1]


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, worker_num: int = 1, role=Role.WORKER,
                 **kwargs):
        self._id = current_id
        self._n = worker_num
        self._role = role

    def worker_num(self) -> int:
        return self._n

    def worker_index(self) -> int:
        return self._id

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER
