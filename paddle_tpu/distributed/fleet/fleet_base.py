"""Fleet singleton (reference: fleet/base/fleet_base.py:125 init,
:544 distributed_optimizer, :920 minimize + strategy_compiler.py chain)."""

from __future__ import annotations

from typing import Optional

from .distributed_strategy import DistributedStrategy
from .meta_optimizers import (AMPOptimizer, GradientMergeOptimizer,
                              RecomputeOptimizer, insert_grad_allreduce,
                              maybe_swap_large_batch_optimizer)
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._is_collective = True
        self._strategy: Optional[DistributedStrategy] = None

    # -- lifecycle -----------------------------------------------------------
    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None):
        from ..parallel import init_parallel_env

        self._is_collective = is_collective
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        self._strategy = strategy
        init_parallel_env()
        return self

    def _assert_init(self):
        if self._role_maker is None:
            self.init()

    # -- topology ------------------------------------------------------------
    def worker_num(self) -> int:
        self._assert_init()
        n = self._role_maker.worker_num()
        if n > 1:
            return n
        # single-process SPMD: dp axis of the active mesh is the worker count
        from ...parallel.mesh import get_mesh

        mesh = get_mesh()
        if mesh is not None and "dp" in mesh.shape:
            return mesh.shape["dp"]
        return n

    def worker_index(self) -> int:
        self._assert_init()
        return self._role_maker.worker_index()

    def is_first_worker(self) -> bool:
        self._assert_init()
        return self._role_maker.is_first_worker()

    def is_worker(self) -> bool:
        self._assert_init()
        return self._role_maker.is_worker()

    def is_server(self) -> bool:
        self._assert_init()
        return self._role_maker.is_server()

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    # -- optimizer -----------------------------------------------------------
    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy] = None):
        self._assert_init()
        self._strategy = strategy or self._strategy or DistributedStrategy()
        return DistributedOptimizer(self, optimizer, self._strategy)


class DistributedOptimizer:
    """Applies the meta-optimizer chain then the DP transpile
    (reference order, strategy_compiler.py: recompute → amp → … →
    graph_execution last)."""

    def __init__(self, fleet_obj: Fleet, inner, strategy: DistributedStrategy):
        self.fleet = fleet_obj
        self.strategy = strategy
        inner = maybe_swap_large_batch_optimizer(inner, strategy)
        if strategy.recompute:
            inner = RecomputeOptimizer(
                inner, strategy.recompute_configs.get("checkpoints", []))
        if strategy.amp:
            inner = AMPOptimizer(inner, strategy.amp_configs)
        if strategy.gradient_merge:
            inner = GradientMergeOptimizer(
                inner, strategy.gradient_merge_configs.get("k_steps", 1),
                strategy.gradient_merge_configs.get("avg", True))
        if strategy.dgc:
            from .meta_optimizers import DGCOptimizer

            inner = DGCOptimizer(inner, strategy.dgc_configs,
                                 nranks=fleet_obj.worker_num())
        if strategy.localsgd:
            from .meta_optimizers import LocalSGDOptimizer

            inner = LocalSGDOptimizer(inner, strategy.localsgd_configs,
                                      nranks=fleet_obj.worker_num())
        if strategy.sharding:
            # ZeRO stage-1/2: replaces the grad allreduce tail with the
            # reduce-scatter → sharded update → allgather schedule
            if strategy.dgc or strategy.localsgd or strategy.gradient_merge:
                on = [k for k in ("dgc", "localsgd", "gradient_merge")
                      if getattr(strategy, k)]
                raise ValueError(
                    f"strategy.sharding composes with amp/recompute/"
                    f"lars/lamb but not with {on} — they own the gradient "
                    f"exchange themselves")
            from .meta_optimizers import ShardingOptimizer

            inner = ShardingOptimizer(inner, strategy.sharding_configs,
                                      nranks=fleet_obj.worker_num())
        self.inner = inner
        # localsgd replaces grad allreduce with periodic param averaging;
        # dgc carries its own (compressed-grad) allreduce; sharding
        # reduce-scatters instead of allreducing
        self._skip_grad_allreduce = bool(strategy.localsgd or strategy.dgc
                                         or strategy.sharding)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.inner.backward(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        # DP allreduce before the update ops (graph_execution equivalent)
        if not self._skip_grad_allreduce:
            insert_grad_allreduce(loss.block.program, params_grads,
                                  self.fleet.worker_num())
        ops = self.inner.apply_gradients(params_grads)
        return ops, params_grads

    def __getattr__(self, item):
        return getattr(self.inner, item)


fleet = Fleet()


def init(role_maker=None, is_collective: bool = True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def worker_num() -> int:
    return fleet.worker_num()


def worker_index() -> int:
    return fleet.worker_index()


def is_first_worker() -> bool:
    return fleet.is_first_worker()


def barrier_worker():
    return fleet.barrier_worker()
