"""Fleet v2 orchestration (reference: python/paddle/distributed/fleet/).

fleet.init → role maker (env parse / jax.distributed init);
fleet.distributed_optimizer(opt, strategy) → meta-optimizer chain;
minimize() rewrites the Program per strategy then applies the inner
optimizer (reference: fleet_base.py:125,544,920 + strategy_compiler.py:112).
"""

from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (Fleet, fleet, init, distributed_optimizer,  # noqa: F401
                         worker_num, worker_index, is_first_worker,
                         barrier_worker)
from .role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
from . import meta_optimizers  # noqa: F401
