"""Meta-optimizers — strategy-driven Program rewrites.

Capability mirror of python/paddle/distributed/fleet/meta_optimizers/
(amp_optimizer.py, recompute_optimizer.py, gradient_merge_optimizer.py,
graph_execution_optimizer.py, lars_optimizer.py, lamb_optimizer.py,
localsgd_optimizer.py, dgc_optimizer.py) + transpiler/collective.py:178
GradAllReduce. Each wraps an inner Optimizer and rewrites the Program:

* AMP        → bf16 cast insertion on MXU ops (+ optional loss-scaling ops
               for API parity; bf16 on TPU needs no scaling)
* Recompute  → forward segments become remat'd block_call ops
               (jax.checkpoint at lowering — real memory savings, unlike the
               reference's grad-time subgraph re-emission, backward.py:689)
* GradientMerge → grad accumulators + conditional_block'd update every k steps
* DP         → scale(1/n) + c_allreduce_sum on every grad (runs under
               shard_map; XLA emits the ICI allreduce)
* LARS/LAMB  → swap the inner optimizer for the large-batch variant
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core import unique_name
from ...core.backward import GRAD_SUFFIX
from ...core.ir import Block, OpDesc, OpRole, Program, default_main_program
from ...layers import nn as L


# ---------------------------------------------------------------------------
# DP: gradient allreduce transpile
# ---------------------------------------------------------------------------

def insert_grad_allreduce(program: Program, params_grads, nranks: int,
                          axis_name="dp", average: bool = True):
    """Append [scale(1/n) +] c_allreduce_sum for each grad
    (reference: transpiler/collective.py GradAllReduce.transpile:178).
    MUST be called between backward() and apply_gradients(): the executor
    runs ops in block order, so allreduce ops appended after the optimizer
    ops would rebind the grad names only after the update consumed them.

    average=True is classic DP (per-rank mean losses → grads averaged);
    average=False is for programs whose loss is already globally normalised
    via in-program c_allreduce_sum (e.g. sequence-parallel token losses) —
    per-rank grads are partials of the SAME global loss, so they sum.
    axis_name may be a tuple (e.g. ("dp", "sp"))."""
    if nranks <= 1:
        return
    block = program.global_block()
    with program._role_guard(OpRole.Backward):
        for p, g in params_grads:
            if average:
                block.append_op("scale", {"X": [g]}, {"Out": [g]},
                                {"scale": 1.0 / nranks,
                                 "op_role_var": [p.name, g.name]})
            block.append_op("c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                            {"axis_name": axis_name, "ring_id": 0,
                             "nranks": nranks,
                             "op_role_var": [p.name, g.name]})


def rewrite_sync_batch_norm(program: Program, axis_name="dp"):
    """Flip every batch_norm op to sync_batch_norm (reference:
    BuildStrategy.sync_batch_norm — framework/ir/sync_batch_norm_pass.cc
    rewrites op type so stats allreduce across ranks). MUST run BEFORE
    backward() so the grad maker re-traces the sync forward (its psum
    transposes into the reference grad kernel's cross-rank reductions)."""
    # guard pass FIRST (grad ops sit after forward ops in block order —
    # mutating while scanning would leave the program half-rewritten
    # when the raise fires)
    for block in program.blocks:
        for op in block.ops:
            if op.type == "__vjp_grad__" and \
                    op.attrs.get("fwd_type") == "batch_norm":
                raise ValueError(
                    "rewrite_sync_batch_norm must run BEFORE backward(): a "
                    "batch_norm grad op already exists and would keep rank-"
                    "local statistics, silently desyncing fwd and bwd")
    n = 0
    for block in program.blocks:
        for op in block.ops:
            if op.type == "batch_norm":
                op.type = "sync_batch_norm"
                op.attrs.setdefault("axis_name", axis_name)
                n += 1
    return n


# ---------------------------------------------------------------------------
# AMP: bf16 rewrite + loss scaling
# ---------------------------------------------------------------------------

AMP_WHITE_LIST = {"matmul", "matmul_v2", "mul", "conv2d", "depthwise_conv2d",
                  "bmm", "flash_attention", "ring_attention"}
AMP_BLACK_LIST = {"softmax_with_cross_entropy", "cross_entropy", "layer_norm",
                  "batch_norm", "sync_batch_norm", "mean", "reduce_mean",
                  "softmax", "exp", "log"}


def rewrite_program_bf16(program: Program, white_list=None, black_list=None):
    """Insert bf16 casts on white-list op inputs (reference:
    contrib/mixed_precision/fp16_utils.py cast insertion). Outputs stay bf16
    and re-promote naturally; params remain fp32 masters so grads/optimizer
    math stay fp32."""
    import jax.numpy as jnp

    white = set(white_list or AMP_WHITE_LIST)
    block = program.global_block()
    new_ops: List[OpDesc] = []
    cast_cache: Dict[str, str] = {}
    for op in block.ops:
        if op.type in white:
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    var = block._find_var_recursive(n)
                    if var is None or np.dtype(var.dtype) != np.float32:
                        new_names.append(n)
                        continue
                    cname = cast_cache.get(n)
                    if cname is None:
                        cname = f"{n}.cast_bf16"
                        block.create_var(name=cname, shape=var.shape,
                                         dtype="bfloat16", stop_gradient=False)
                        cop = OpDesc("cast", {"X": [n]}, {"Out": [cname]},
                                     {"out_dtype": "bfloat16",
                                      "op_role": op.attrs.get("op_role", 0)})
                        new_ops.append(cop)
                        cast_cache[n] = cname
                    new_names.append(cname)
                op.inputs[slot] = new_names
        new_ops.append(op)
    block.ops = new_ops
    program._bump_version()


class AMPOptimizer:
    """reference: fleet/meta_optimizers/amp_optimizer.py +
    contrib/mixed_precision/decorator.py OptimizerWithMixedPrecision."""

    def __init__(self, inner, configs: Optional[dict] = None):
        self.inner = inner
        self.configs = configs or {}
        self._loss_scaling_var = None

    def backward(self, loss, **kw):
        rewrite_program_bf16(loss.block.program,
                             white_list=(set(AMP_WHITE_LIST)
                                         | set(self.configs.get(
                                             "custom_white_list", []))))
        if self.configs.get("use_dynamic_loss_scaling"):
            self._loss_scaling_var = L.create_global_var(
                [1], self.configs.get("init_loss_scaling", 32768.0),
                "float32", persistable=True,
                name=unique_name.generate("loss_scaling"))
            loss = loss * self._loss_scaling_var
        return self.inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        if self.configs.get("use_dynamic_loss_scaling"):
            params_grads = append_loss_scaling_ops(
                params_grads, self._loss_scaling_var)
        return self.inner.apply_gradients(params_grads)

    def minimize(self, loss, **kw):
        pg = self.backward(loss, **kw)
        ops = self.apply_gradients(pg)
        return ops, pg

    def __getattr__(self, item):
        return getattr(self.inner, item)


def append_loss_scaling_ops(params_grads, scale_var):
    """check_finite_and_unscale + update_loss_scaling (reference:
    operators/amp/*). Kept for API parity — bf16 needs no scaling, but fp16
    flows and the strategy knob still exercise this path."""
    block = default_main_program().current_block()
    good = L.create_global_var([1], 0, "int32", persistable=True,
                               name=unique_name.generate("good_steps"))
    bad = L.create_global_var([1], 0, "int32", persistable=True,
                              name=unique_name.generate("bad_steps"))
    grads = [g for _, g in params_grads]
    found_inf = block.create_var(
        name=unique_name.generate("found_inf"), dtype="bool", shape=(1,),
        stop_gradient=True)
    block.append_op("check_finite_and_unscale",
                    {"X": grads, "Scale": [scale_var]},
                    {"Out": grads, "FoundInfinite": [found_inf]}, {})
    block.append_op("update_loss_scaling",
                    {"X": grads, "FoundInfinite": [found_inf],
                     "PrevLossScaling": [scale_var], "InGoodSteps": [good],
                     "InBadSteps": [bad]},
                    {"Out": grads, "LossScaling": [scale_var],
                     "OutGoodSteps": [good], "OutBadSteps": [bad]},
                    {"incr_every_n_steps": 1000, "decr_every_n_nan_or_inf": 2,
                     "incr_ratio": 2.0, "decr_ratio": 0.5})
    return params_grads


# ---------------------------------------------------------------------------
# Recompute: segment remat
# ---------------------------------------------------------------------------

def _segment_external_io(ops: List[OpDesc], block: Block,
                         later_reads: set) -> Tuple[List[str], List[str]]:
    produced = set()
    reads: List[str] = []
    for op in ops:
        for n in op.input_names():
            if n not in produced and n not in reads:
                reads.append(n)
        produced.update(op.output_names())
    outs = [n for n in dict.fromkeys(
        n for op in ops for n in op.output_names())
        if n in later_reads]
    return reads, outs


class RecomputeOptimizer:
    """reference: optimizer.py:4547 RecomputeOptimizer /
    fleet recompute_optimizer.py. Forward ops between user checkpoints are
    folded into remat'd block_call ops before backward, so the whole segment
    is recomputed in the backward pass (jax.checkpoint under the hood)."""

    def __init__(self, inner, checkpoints: Optional[List] = None):
        self.inner = inner
        self._checkpoints = [c if isinstance(c, str) else c.name
                             for c in (checkpoints or [])]

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = [c if isinstance(c, str) else c.name
                             for c in checkpoints]

    def _rewrite(self, program: Program, loss_name: str):
        block = program.global_block()
        ckpts = set(self._checkpoints)
        if not ckpts:
            return
        # split forward ops into segments at checkpoint producers
        segments: List[List[OpDesc]] = [[]]
        for op in block.ops:
            segments[-1].append(op)
            if any(n in ckpts for n in op.output_names()):
                segments.append([])
        if not segments[-1]:
            segments.pop()
        # later_reads per segment = union of inputs of later segments + loss
        suffix_reads: List[set] = [set() for _ in segments]
        acc: set = {loss_name}
        for i in range(len(segments) - 1, -1, -1):
            suffix_reads[i] = set(acc)
            for op in segments[i]:
                acc.update(op.input_names())
        new_ops: List[OpDesc] = []
        for i, seg in enumerate(segments):
            last = i == len(segments) - 1
            persist_out = any(
                block.has_var(n) and block.var(n).persistable
                for op in seg for n in op.output_names())
            if last or len(seg) < 2 or persist_out:
                new_ops.extend(seg)  # tail / trivial / stateful: keep inline
                continue
            reads, outs = _segment_external_io(
                seg, block, suffix_reads[i] | ckpts)
            sub = Block(program, len(program.blocks), 0)
            sub.ops = list(seg)
            program.blocks.append(sub)
            new_ops.append(OpDesc(
                "block_call", {"X": reads}, {"Out": outs},
                {"sub_block": sub, "input_names": reads,
                 "output_names": outs, "remat": True,
                 "op_role": OpRole.Forward}))
        block.ops = new_ops
        program._bump_version()

    def backward(self, loss, **kw):
        self._rewrite(loss.block.program, loss.name)
        return self.inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self.inner.apply_gradients(params_grads)

    def minimize(self, loss, **kw):
        pg = self.backward(loss, **kw)
        ops = self.apply_gradients(pg)
        return ops, pg

    def __getattr__(self, item):
        return getattr(self.inner, item)


# ---------------------------------------------------------------------------
# Gradient merge (accumulation)
# ---------------------------------------------------------------------------

class GradientMergeOptimizer:
    """reference: optimizer.py:5025 GradientMergeOptimizer — accumulate k
    microbatch grads, then run the real update inside a conditional_block."""

    def __init__(self, inner, k_steps: int = 1, avg: bool = True):
        self.inner = inner
        self.k_steps = int(k_steps)
        self.avg = avg

    def backward(self, loss, **kw):
        return self.inner.backward(loss, **kw)

    def minimize(self, loss, **kw):
        pg = self.backward(loss, **kw)
        ops = self.apply_gradients(pg)
        return ops, pg

    def apply_gradients(self, params_grads):
        k = self.k_steps
        if k <= 1:
            return self.inner.apply_gradients(params_grads)
        program = default_main_program()
        block = program.global_block()
        with program._role_guard(OpRole.Optimize):
            # accumulate
            acc_pg = []
            for p, g in params_grads:
                acc = L.create_global_var(list(p.shape), 0.0, "float32",
                                          persistable=True,
                                          name=f"{p.name}@GradAcc")
                block.append_op("sum", {"X": [acc, g]}, {"Out": [acc]}, {})
                acc_pg.append((p, block.var(acc.name)))
            # step counter + fire condition
            counter = L.create_global_var([1], 0.0, "float32",
                                          persistable=True,
                                          name=unique_name.generate("gm_step"))
            block.append_op("increment", {"X": [counter]}, {"Out": [counter]},
                            {"step": 1.0})
            kvar = L.fill_constant([1], "float32", float(k))
            rem = block.create_var(name=unique_name.generate("gm_rem"),
                                   stop_gradient=True)
            block.append_op("elementwise_mod", {"X": [counter], "Y": [kvar]},
                            {"Out": [rem]}, {"axis": -1})
            zero = L.fill_constant([1], "float32", 0.0)
            fire = block.create_var(name=unique_name.generate("gm_fire"),
                                    dtype="bool", stop_gradient=True)
            block.append_op("equal", {"X": [rem], "Y": [zero]},
                            {"Out": [fire]}, {})

            # build the update sub-block: scale acc, inner update, reset acc
            sub = program.create_block(parent_idx=0)
            try:
                scaled_pg = []
                for p, acc in acc_pg:
                    if self.avg:
                        sub.append_op("scale", {"X": [acc]}, {"Out": [acc]},
                                      {"scale": 1.0 / k})
                    scaled_pg.append((p, acc))
                self.inner.apply_gradients(scaled_pg)
                for p, acc in acc_pg:
                    sub.append_op("scale", {"X": [acc]}, {"Out": [acc]},
                                  {"scale": 0.0})
            finally:
                program.rollback()

            reads, _ = _segment_external_io(sub.ops, sub, set())
            reads = [n for n in dict.fromkeys(reads)]
            written = list(dict.fromkeys(
                n for op in sub.ops for n in op.output_names()))
            # outputs must be carried through the false branch too
            io_names = list(dict.fromkeys(reads + written))
            block.append_op(
                "conditional_block",
                {"Cond": [fire], "X": io_names},
                {"Out": written},
                {"sub_block": sub, "input_names": io_names,
                 "output_names": written})
        return []

    def __getattr__(self, item):
        return getattr(self.inner, item)


# ---------------------------------------------------------------------------
# ZeRO sharding: optimizer-state / gradient partitioning over dp
# ---------------------------------------------------------------------------

class ShardingOptimizer:
    """ZeRO stage-1/2 data-parallel sharding (Rajbhandari et al. 2020;
    reference: DistributedStrategy.sharding/sharding_configs +
    fleet/meta_optimizers/sharding_optimizer.py): optimizer state — and,
    at stage 2, the gradient reduction itself — is partitioned over the
    dp axis instead of replicated per rank.

    Transpile, per (param, grad), all inside the SAME single program (it
    runs under the executor's shard_map wrap, so XLA schedules the
    per-param dp collectives to overlap with the remaining backward
    compute instead of one blocking tail allreduce):

    * stage 2: ``scale(1/n) → flatten/pad → c_reducescatter`` — each rank
      receives only its 1/n grad shard (lax.psum_scatter);
    * stage 1: ``scale(1/n) → flatten/pad → c_allreduce_sum → c_scatter``
      — classic full allreduce, then the local shard is cut (optimizer
      state still shards; grad traffic unchanged);
    * update: a padded 1-D PROXY param shard (``c_scatter`` of the
      flattened param) feeds the inner optimizer's own update op; the
      inner's accumulators are created AT SHARD GEOMETRY ([padded]
      global, annotated ``('dp',)`` → 1/n bytes per device) — the ZeRO
      memory win;
    * gather: ``c_allgather`` the updated shard → slice/reshape →
      ``assign`` back into the full (replicated) param.

    Numerics are bitwise-identical to grad-allreduce DP: psum_scatter
    and psum produce identical per-element sums, the update math is
    elementwise, and the zero-padded tail (zero param, zero grad, zero
    moments) never moves. Composes unchanged with Executor.run_steps
    K-step fusion — the whole schedule lives inside the scanned step
    body. Params stay full/replicated in the scope, so checkpoints keep
    the PR 5 exact-resume format and reshard transparently on load.
    """

    def __init__(self, inner, configs: Optional[dict] = None,
                 nranks: int = 1, axis_name="dp"):
        cfgs = dict(configs or {})
        self.inner = inner
        self.stage = int(cfgs.get("stage", cfgs.get("zero_stage", 1)))
        if self.stage not in (1, 2):
            raise ValueError(
                f"ShardingOptimizer: stage must be 1 (optimizer state) or "
                f"2 (+ gradients), got {self.stage}")
        degree = int(cfgs.get("sharding_degree", 0) or 0)
        self.nranks = degree if degree > 1 else int(nranks)
        self.axis_name = axis_name
        self._state_var_names: List[str] = []

    def backward(self, loss, **kw):
        return self.inner.backward(loss, **kw)

    def minimize(self, loss, **kw):
        pg = self.backward(loss, **kw)
        return self.apply_gradients(pg), pg

    def apply_gradients(self, params_grads):
        n = self.nranks
        if n <= 1:
            return self.inner.apply_gradients(params_grads)
        if getattr(self.inner, "_grad_clip", None) is not None:
            raise ValueError(
                "ShardingOptimizer: the inner optimizer's grad_clip is not "
                "supported — global-norm clipping needs cross-shard norms; "
                "drop the clip or disable sharding")
        from ...core import telemetry
        from ...parallel.api import shard_tensor
        from ...regularizer import append_regularization_ops

        program = default_main_program()
        block = program.current_block()
        ax = self.axis_name
        rs_bytes = ar_bytes = ag_bytes = 0

        def new_var(stem, shape, dtype):
            return block.create_var(name=unique_name.generate(stem),
                                    shape=tuple(shape), dtype=dtype,
                                    stop_gradient=True)

        # -- grad reduction: reduce-scatter (stage 2) / allreduce+cut
        #    (stage 1) into per-rank 1-D shards --------------------------
        meta = []                      # (param, grad_shard, numel, padded)
        with program._role_guard(OpRole.Backward):
            for p, g in params_grads:
                numel = int(np.prod(p.shape))
                padded = -(-numel // n) * n
                dtype = str(np.dtype(p.dtype))
                itemsize = np.dtype(p.dtype).itemsize
                block.append_op("scale", {"X": [g]}, {"Out": [g]},
                                {"scale": 1.0 / n,
                                 "op_role_var": [p.name, g.name]})
                gflat = new_var(f"{g.name}@zflat", (numel,), dtype)
                block.append_op("reshape", {"X": [g]}, {"Out": [gflat]},
                                {"shape": [-1]})
                if padded != numel:
                    gpad = new_var(f"{g.name}@zpad", (padded,), dtype)
                    block.append_op("pad", {"X": [gflat]}, {"Out": [gpad]},
                                    {"paddings": [0, padded - numel],
                                     "pad_value": 0.0})
                    gflat = gpad
                gshard = new_var(f"{g.name}@zshard", (padded,), dtype)
                if self.stage >= 2:
                    block.append_op("c_reducescatter", {"X": [gflat]},
                                    {"Out": [gshard]},
                                    {"axis_name": ax, "nranks": n,
                                     "op_role_var": [p.name, g.name]})
                    rs_bytes += padded * itemsize
                else:
                    block.append_op("c_allreduce_sum", {"X": [gflat]},
                                    {"Out": [gflat]},
                                    {"axis_name": ax, "nranks": n,
                                     "op_role_var": [p.name, g.name]})
                    block.append_op("c_scatter", {"X": [gflat]},
                                    {"Out": [gshard]},
                                    {"axis_name": ax, "nranks": n})
                    ar_bytes += padded * itemsize
                meta.append((p, gshard, numel, padded))

        # -- sharded update: proxy param shards drive the inner
        #    optimizer's unmodified update ops ---------------------------
        with program._role_guard(OpRole.Optimize):
            self.inner._create_global_learning_rate()
            shard_pgs = []
            proxies = {}
            for p, gshard, numel, padded in meta:
                dtype = str(np.dtype(p.dtype))
                pflat = new_var(f"{p.name}@zflat", (numel,), dtype)
                block.append_op("reshape", {"X": [p]}, {"Out": [pflat]},
                                {"shape": [-1]})
                if padded != numel:
                    ppad = new_var(f"{p.name}@zpad", (padded,), dtype)
                    block.append_op("pad", {"X": [pflat]}, {"Out": [ppad]},
                                    {"paddings": [0, padded - numel],
                                     "pad_value": 0.0})
                    pflat = ppad
                proxy = new_var(f"{p.name}@zero", (padded,), dtype)
                # the proxy's explicit ('dp',) spec is what the inner's
                # _add_accumulator copies onto the moments — per-device
                # optimizer state becomes 1/n
                shard_tensor(proxy, (ax,))
                proxy.regularizer = getattr(p, "regularizer", None)
                block.append_op("c_scatter", {"X": [pflat]},
                                {"Out": [proxy]},
                                {"axis_name": ax, "nranks": n})
                proxies[p.name] = proxy
                shard_pgs.append((proxy, gshard))
            shard_pgs = append_regularization_ops(
                shard_pgs, self.inner.regularization)
            self.inner._create_accumulators(
                block, [proxy for proxy, _ in shard_pgs])
            for pg in shard_pgs:
                self.inner._append_optimize_op(block, pg)
            # gather the updated shards back into the full params
            for p, _, numel, padded in meta:
                dtype = str(np.dtype(p.dtype))
                itemsize = np.dtype(p.dtype).itemsize
                proxy = proxies[p.name]
                pfull = new_var(f"{p.name}@zgather", (padded,), dtype)
                block.append_op("c_allgather", {"X": [proxy]},
                                {"Out": [pfull]},
                                {"axis_name": ax, "nranks": n})
                ag_bytes += padded * itemsize
                if padded != numel:
                    pcut = new_var(f"{p.name}@zcut", (numel,), dtype)
                    block.append_op("slice", {"Input": [pfull]},
                                    {"Out": [pcut]},
                                    {"axes": [0], "starts": [0],
                                     "ends": [numel]})
                    pfull = pcut
                pout = new_var(f"{p.name}@znew", tuple(p.shape), dtype)
                block.append_op("reshape", {"X": [pfull]}, {"Out": [pout]},
                                {"shape": list(p.shape)})
                block.append_op("assign", {"X": [pout]}, {"Out": [p]}, {})

        # the accumulators the inner created for the PROXIES are the
        # sharded optimizer state (report_state_sharding measures them)
        proxy_names = {proxy.name for proxy in proxies.values()}
        self._state_var_names = sorted(
            var.name
            for per_param in getattr(self.inner, "_accumulators", {}).values()
            for pname, var in per_param.items() if pname in proxy_names)

        # elastic-resize metadata: every padded-geometry state var's
        # LOGICAL numel. The padded length is a function of the dp
        # degree (-(-numel // n) * n), so a checkpoint saved at one
        # degree restores into another by unpad-to-numel / repad-to-new
        # (parallel/zero_regroup.py) — this map is what tells the
        # restore which leading slice is real data
        geom_by_proxy = {proxies[p.name].name: (numel, padded)
                         for p, _, numel, padded in meta}
        zero_meta = {}
        for per_param in getattr(self.inner, "_accumulators", {}).values():
            for pname, var in per_param.items():
                geom = geom_by_proxy.get(pname)
                if geom is None:
                    continue
                numel, padded = geom
                # only the PADDED-geometry accumulators regroup; scalar
                # state (beta-pow etc., shape [1]) is degree-independent
                if tuple(var.shape) == (padded,) and padded != 1:
                    zero_meta[var.name] = int(numel)

        # static per-step collective payloads: the executor books these
        # per dispatch (sharding.*_bytes counters + the trace span)
        program._zero_stage = self.stage
        program._zero_degree = n
        program._zero_state_numel = zero_meta
        program._sharding_bytes = {"reduce_scatter": rs_bytes,
                                   "allreduce": ar_bytes,
                                   "allgather": ag_bytes}
        telemetry.gauge_set("sharding.zero_stage", self.stage)
        telemetry.gauge_set("sharding.degree", n)
        telemetry.counter_add("sharding.params_sharded", len(meta))
        return []

    def report_state_sharding(self, scope) -> Dict[str, int]:
        """Measure live optimizer-state bytes (global logical size vs the
        max resident on any one device) from the scope arrays' actual
        shardings — the ZeRO acceptance gauge: per-device bytes ~1/dp of
        an unsharded optimizer. Sets sharding.optimizer_state_bytes and
        sharding.optimizer_state_bytes_per_device."""
        from ...core import telemetry

        total = 0
        per_device: Dict[object, int] = {}
        for name in self._state_var_names:
            v = scope.find_var(name)
            if v is None:
                continue
            shards = getattr(v, "addressable_shards", None)
            if shards:
                total += int(v.nbytes)
                for s in shards:
                    nb = int(np.prod(s.data.shape or (1,))
                             * np.dtype(s.data.dtype).itemsize)
                    per_device[s.device] = per_device.get(s.device, 0) + nb
            else:
                a = np.asarray(v)
                total += int(a.nbytes)
                per_device.setdefault("host", 0)
                per_device["host"] += int(a.nbytes)
        per_dev = max(per_device.values(), default=0)
        telemetry.gauge_set("sharding.optimizer_state_bytes", total)
        telemetry.gauge_set("sharding.optimizer_state_bytes_per_device",
                            per_dev)
        # the HBM ledger (core/costmodel.py) prefers the sharded
        # per-device figure over the capture-time unsharded estimate —
        # recompose mem.hbm_total_bytes now that it moved
        from ...core import costmodel

        costmodel.refresh_ledger()
        return {"total_bytes": total, "per_device_bytes": per_dev,
                "state_vars": len(self._state_var_names)}

    def __getattr__(self, item):
        return getattr(self.inner, item)


# ---------------------------------------------------------------------------
# LARS / LAMB swaps + stubs
# ---------------------------------------------------------------------------

def maybe_swap_large_batch_optimizer(inner, strategy):
    """reference: lars_optimizer.py / lamb_optimizer.py meta-optimizers —
    replace Momentum→LarsMomentum, Adam→Lamb when enabled."""
    from ... import optimizer as opt

    if strategy.lars and isinstance(inner, opt.MomentumOptimizer) and \
            not isinstance(inner, opt.LarsMomentumOptimizer):
        return opt.LarsMomentumOptimizer(
            inner._learning_rate, momentum=inner._momentum,
            **strategy.lars_configs)
    if strategy.lamb and isinstance(inner, opt.AdamOptimizer) and \
            not isinstance(inner, opt.LambOptimizer):
        return opt.LambOptimizer(
            inner._learning_rate,
            lamb_weight_decay=strategy.lamb_configs.get("lamb_weight_decay",
                                                        0.01))
    return inner


class LocalSGDOptimizer:
    """reference: fleet/meta_optimizers/localsgd_optimizer.py +
    transpiler/collective.py:270 — each rank takes k LOCAL optimizer
    steps (grads are NOT allreduced), then params are averaged across
    the dp ring every k-th step via the local_sgd_sync op."""

    def __init__(self, inner, configs: Optional[dict] = None,
                 nranks: int = 1, axis_name="dp"):
        self.inner = inner
        self.k_steps = int((configs or {}).get("k_steps", 1))
        self.nranks = int(nranks)
        self.axis_name = axis_name

    def backward(self, loss, **kw):
        return self.inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        ops = self.inner.apply_gradients(params_grads)
        block = default_main_program().current_block()
        for p, _ in params_grads:
            block.append_op(
                "local_sgd_sync", {"X": [p]}, {"Out": [p]},
                {"axis_name": self.axis_name, "nranks": self.nranks,
                 "k_steps": self.k_steps})
        return ops

    def minimize(self, loss, **kw):
        pg = self.backward(loss, **kw)
        return self.apply_gradients(pg), pg

    def __getattr__(self, item):
        return getattr(self.inner, item)


class DGCOptimizer:
    """reference: fleet/meta_optimizers/dgc_optimizer.py +
    operators/dgc_op.cc (DGCMomentumOptimizer optimizer.py:1185): deep
    gradient compression — momentum-corrected top-k sparsification of
    each grad BEFORE the allreduce; the carry buffers (U momentum, V
    residual) keep the unsent mass. The dgc op ITSELF performs the
    momentum correction, so the parameter update applies the released
    gradient with plain SGD (the reference's dgc_momentum_op.h switches
    momentum -> sgd once DGC is active past rampup; applying the inner
    momentum again would square the steady-state multiplier). On ICI
    the sparse exchange buys nothing (round-1 note) but the compression
    math and convergence behaviour are reproduced — the capability."""

    def __init__(self, inner, configs: Optional[dict] = None,
                 nranks: int = 1, axis_name="dp"):
        self.inner = inner
        cfgs = configs or {}
        # reference semantics: sparsity = fraction DROPPED (default
        # 0.999 keeps the top 0.1%); the dgc op's `ratios` attr is the
        # fraction KEPT
        sparsity = cfgs.get("sparsity", [0.999])
        if isinstance(sparsity, (list, tuple)):
            sparsity = sparsity[0]
        self.ratio = max(1.0 - float(sparsity), 1e-6)
        self.momentum = float(cfgs.get("momentum", 0.9))
        self.nranks = int(nranks)
        self.axis_name = axis_name

    def backward(self, loss, **kw):
        return self.inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        block = default_main_program().current_block()
        with block.program._role_guard(OpRole.Backward):
            for p, g in params_grads:
                u = L.create_global_var(list(p.shape), 0.0, "float32",
                                        persistable=True,
                                        name=unique_name.generate(
                                            p.name + "_dgc_u"))
                v = L.create_global_var(list(p.shape), 0.0, "float32",
                                        persistable=True,
                                        name=unique_name.generate(
                                            p.name + "_dgc_v"))
                block.append_op(
                    "dgc",
                    {"U": [u], "V": [v], "Grad": [g], "Param": [p]},
                    {"U_out": [u], "V_out": [v], "EncodeGrad": [g],
                     "Grad_out": [g], "GatherBuff": [g]},
                    {"m": self.momentum, "ratios": self.ratio})
                if self.nranks > 1:
                    block.append_op(
                        "c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                        {"axis_name": self.axis_name,
                         "nranks": self.nranks})
                    block.append_op(
                        "scale", {"X": [g]}, {"Out": [g]},
                        {"scale": 1.0 / self.nranks})
        # SGD update with the inner optimizer's learning rate: the dgc
        # op already applied the momentum correction
        with block.program._role_guard(OpRole.Optimize):
            self.inner._create_global_learning_rate()
            for p, g in params_grads:
                block.append_op(
                    "sgd",
                    {"Param": [p], "Grad": [g],
                     "LearningRate": [self.inner._lr_var]},
                    {"ParamOut": [p]}, {})
        return []

    def minimize(self, loss, **kw):
        pg = self.backward(loss, **kw)
        return self.apply_gradients(pg), pg

    def __getattr__(self, item):
        return getattr(self.inner, item)
