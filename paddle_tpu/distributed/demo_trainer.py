"""Deterministic trainer/pserver child for the launch.py orchestrator.

This is the workload side of the process-level crash-survival story
(tests/test_orchestrator.py, tools/chaos_check.py --orchestrator): a
small fc net trained with a deterministic data stream, speaking the
orchestrator's full child contract —

* env-carried identity: PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM (set by
  Orchestrator via distributed/parallel.cluster_env), PADDLE_ROLE;
* control channel: one ``PT_ORCH_READY`` announce once serving, one
  ``PT_ORCH_HB {"step": n}`` heartbeat per step;
* SIGTERM = drain: rank 0 runs under ElasticRunner with
  install_signal_handlers(), so the drain command force-checkpoints and
  BOUND-joins the async writer before exit 0 (the orchestrator's
  SIGKILL escalation is the backstop, not the plan);
* crash-restart resume: every rank restores the newest VERIFIED
  checkpoint from the shared --ckpt-dir at startup, so a respawned or
  relaunched-at-new-world child continues the step sequence.

Every rank computes the FULL global batch (mirrored data parallelism),
which makes the parameter trajectory — and therefore the ``LOSS <step>
<value>`` rows rank 0 appends to --out — invariant to world size: the
2→3→2 resize gate diffs those rows bitwise against an uninterrupted
single-process run. --crash-at K SIGKILLs the process at step K every
life, turning this child into the deterministic crash-loop the
restart-budget-exhaustion test needs; --step-delay-ms widens the
mid-step kill window for chaos.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def build_model():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.initializer import Xavier

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64",
                            stop_gradient=True)
        h = layers.fc(x, 32, act="relu",
                      param_attr=pt.ParamAttr(name="w0",
                                              initializer=Xavier(seed=7)),
                      bias_attr=pt.ParamAttr(name="b0"))
        logits = layers.fc(h, 4,
                           param_attr=pt.ParamAttr(name="w1",
                                                   initializer=Xavier(
                                                       seed=8)),
                           bias_attr=pt.ParamAttr(name="b1"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits,
                                                             label))
        opt = pt.optimizer.SGDOptimizer(0.25)
        opt.minimize(loss)
    return main, startup, loss


def batch_for(step: int):
    """The FULL global batch for one step — identical on every rank, so
    the parameter trajectory is world-size invariant."""
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(16, 16).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.int64)
    return x, y


def run_trainer(args) -> int:
    import paddle_tpu as pt
    from paddle_tpu.distributed.elastic import ElasticRunner
    from paddle_tpu.distributed.launch import announce_ready, heartbeat

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    main, startup, loss = build_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)

    out_f = None
    if args.out and rank == 0:
        # O_APPEND + per-row flush: a SIGKILL never loses a committed
        # row, and a respawned life appends after its predecessor's
        out_f = open(args.out, "a", buffering=1)

    def step_fn(step: int):
        if args.crash_at >= 0 and step == args.crash_at:
            os.kill(os.getpid(), signal.SIGKILL)
        if args.step_delay_ms > 0:
            time.sleep(args.step_delay_ms / 1e3)
        x, y = batch_for(step)
        out = exe.run(main, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        value = float(np.asarray(out[0]).reshape(-1)[0])
        if out_f is not None:
            out_f.write(f"LOSS {step} {value:.6f}\n")
        heartbeat(step=step)
        return value

    if rank == 0:
        # the saving rank: ElasticRunner owns restore-at-start, the
        # periodic async save, and the SIGTERM drain (force save +
        # bounded writer join)
        runner = ElasticRunner(args.ckpt_dir, program=main, scope=scope,
                               save_interval_steps=args.save_interval,
                               max_restarts=0, world_size=world)
        runner.install_signal_handlers()
        announce_ready(role="trainer", rank=rank, world=world)
        try:
            runner.run(step_fn, args.steps)
        finally:
            runner.close()
            if out_f is not None:
                out_f.close()
        return 0

    # follower ranks: restore to the shared trajectory, run the mirrored
    # step loop, exit 0 on SIGTERM (nothing of theirs needs saving)
    from paddle_tpu.checkpoint import CheckpointManager

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda _s, _f: stop.set())
    signal.signal(signal.SIGINT, lambda _s, _f: stop.set())
    step = CheckpointManager(args.ckpt_dir).restore_latest(main, scope)
    announce_ready(role="trainer", rank=rank, world=world)
    while step < args.steps and not stop.is_set():
        step_fn(step)
        step += 1
    return 0


def run_pserver(args) -> int:
    """A real RPC service child (distributed/ps/rpc.RPCServer) holding a
    kv table — the orchestrator provisions, heartbeats, and respawns it
    exactly like a trainer; chaos_check SIGKILLs it."""
    from paddle_tpu.distributed.launch import announce_ready, heartbeat
    from paddle_tpu.distributed.ps.rpc import RPCServer

    table = {}

    def handler(method, name, arr, aux):
        if method in ("send", "push", "send_grad"):
            table[name] = np.asarray(arr).copy()
            return None, aux
        got = table.get(name)
        if got is None:
            got = np.zeros(1, dtype=np.float32)
        return got, aux

    server = RPCServer("127.0.0.1:0", handler)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda _s, _f: stop.set())
    signal.signal(signal.SIGINT, lambda _s, _f: stop.set())
    announce_ready(role="pserver", endpoint=server.endpoint)
    while not stop.wait(0.5):
        heartbeat(keys=len(table))
    server.shutdown()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic orchestrator child (trainer or "
                    "pserver role)")
    ap.add_argument("--role", default="",
                    choices=("", "trainer", "pserver"),
                    help="default: PADDLE_ROLE env (the orchestrator "
                         "sets it), else trainer")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--ckpt-dir", default="",
                    help="shared checkpoint dir (required for trainers)")
    ap.add_argument("--out", default="",
                    help="rank 0 appends 'LOSS <step> <value>' rows here")
    ap.add_argument("--save-interval", type=int, default=1)
    ap.add_argument("--step-delay-ms", type=float, default=0.0,
                    help="pace steps (widens the chaos kill window)")
    ap.add_argument("--crash-at", type=int, default=-1,
                    help="SIGKILL self at this step, every life — the "
                         "deterministic crash loop for budget tests")
    args = ap.parse_args(argv)
    role = args.role or os.environ.get("PADDLE_ROLE", "trainer")
    if role == "pserver":
        return run_pserver(args)
    if not args.ckpt_dir:
        ap.error("--ckpt-dir is required for trainer role")
    return run_trainer(args)


if __name__ == "__main__":
    sys.exit(main())
