"""Host-side sharded KV store for huge sparse embeddings.

Capability mirror of the reference's large-scale sparse stack
(operators/distributed/large_scale_kv.h SSDSparseTable-style server tables,
framework/fleet/fleet_wrapper.h:111 PullSparseVarsSync / push grads): a
sharded hashmap of id → embedding row living in HOST memory, so embedding
tables far larger than HBM stay off-chip; the hot rows a batch touches are
pulled to device, trained, and pushed back.

TPU design note (SURVEY.md §2.7): the reference distributes this across
pserver processes over gRPC/BRPC. Here shards are in-process (one per
host); multi-host deployment points each host's trainer at its own shard
set with jax.distributed coordinating — the pull/push surface is the same.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.analysis import lockdep


def id_keyed_init(seed: int = 0, scale: float = 0.01):
    """Deterministic per-ID initializer: the row depends only on (seed,
    id), never on shard layout — a table sharded across N pservers
    initialises identically to a single-host table (required for the
    local-vs-distributed parity contract, test_distributed_kv.py).

    Vectorised splitmix64 over the (id, seed, column) lattice (a
    RandomState per missing row costs ~µs each inside the shard lock —
    far too slow for 100k-new-id cold pulls). Rows are uniform in
    [-sqrt(3)·scale, sqrt(3)·scale] (mean 0, std `scale`)."""
    U = np.uint64

    def init(dim, key):
        with np.errstate(over="ignore"):
            x = (U(int(key) & 0xFFFFFFFFFFFFFFFF) * U(0x9E3779B97F4A7C15)
                 + np.arange(dim, dtype=np.uint64) * U(0xBF58476D1CE4E5B9)
                 + U(seed) * U(0x94D049BB133111EB))
            x ^= x >> U(30)
            x *= U(0xBF58476D1CE4E5B9)
            x ^= x >> U(27)
            x *= U(0x94D049BB133111EB)
            x ^= x >> U(31)
        u = (x >> U(11)).astype(np.float64) * (1.0 / (1 << 53))  # [0, 1)
        return ((u * 2.0 - 1.0) * (np.sqrt(3.0) * scale)).astype(np.float32)

    return init


class SparseShard:
    def __init__(self, dim: int, initializer):
        self.dim = dim
        self.table: Dict[int, np.ndarray] = {}
        self.init = initializer          # init(dim, id) -> row
        self.lock = lockdep.lock("kv.shard")

    def pull(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((len(ids), self.dim), np.float32)
        with self.lock:
            for i, key in enumerate(ids):
                row = self.table.get(int(key))
                if row is None:
                    row = self.init(self.dim, int(key)).astype(np.float32)
                    self.table[int(key)] = row
                out[i] = row
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray, lr: float):
        with self.lock:
            for key, g in zip(ids, grads):
                k = int(key)
                row = self.table.get(k)
                if row is None:
                    row = self.init(self.dim, k).astype(np.float32)
                self.table[k] = row - lr * g


class LargeScaleKV:
    """Sharded id → row store with SGD push (reference: large_scale_kv.h
    + DownpourWorker pull/push flow, downpour_worker.cc)."""

    def __init__(self, dim: int, num_shards: int = 8, seed: int = 0,
                 initializer: Optional[Callable] = None):
        self.dim = dim
        init = initializer or id_keyed_init(seed)
        self.shards = [SparseShard(dim, init) for _ in range(num_shards)]

    def _shard_of(self, ids: np.ndarray):
        return np.mod(ids, len(self.shards)).astype(np.int64)

    def pull(self, ids) -> np.ndarray:
        """Gather rows for (possibly duplicated) ids — one row per id."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), self.dim), np.float32)
        sh = self._shard_of(ids)
        for s, shard in enumerate(self.shards):
            mask = sh == s
            if mask.any():
                out[mask] = shard.pull(ids[mask])
        return out

    def push(self, ids, grads, lr: float = 0.01):
        """Scatter-add gradients (duplicate ids accumulate) then SGD."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(acc, inv, grads)
        sh = self._shard_of(uniq)
        for s, shard in enumerate(self.shards):
            mask = sh == s
            if mask.any():
                shard.push(uniq[mask], acc[mask], lr)

    def size(self) -> int:
        return sum(len(s.table) for s in self.shards)

    def save(self, path: str):
        from ..io import atomic_savez

        ids, rows = [], []
        for s in self.shards:
            with s.lock:
                for k, v in s.table.items():
                    ids.append(k)
                    rows.append(v)
        # atomic commit: a server killed mid-snapshot must not leave a
        # torn table npz under the final name
        atomic_savez(path, ids=np.asarray(ids, np.int64),
                     rows=np.stack(rows) if rows else
                     np.zeros((0, self.dim), np.float32))

    def load(self, path: str, keep=None) -> int:
        """Ingest a snapshot, re-sharding every row by id AT LOAD time —
        the on-disk order/shard layout is never trusted, so a snapshot
        written under ANY ``num_shards`` restores correctly into this
        table's count (restore into a different count used to silently
        mis-shard when layouts were trusted).

        ``keep(ids) -> bool mask`` filters rows before ingest — the
        cross-server rebalance hook (kv_service.KVTables.load_all):
        when the pserver count changes, every server reads EVERY saved
        snapshot and keeps only the rows ``id % new_count`` routes to
        it. Returns the number of rows ingested."""
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        ids = np.asarray(data["ids"], np.int64)
        rows = data["rows"]
        if keep is not None and len(ids):
            mask = np.asarray(keep(ids), bool)
            ids, rows = ids[mask], rows[mask]
        by_shard: Dict[int, list] = {}
        for k, v in zip(ids, rows):
            by_shard.setdefault(int(k) % len(self.shards), []).append(
                (int(k), v))
        for s, items in by_shard.items():
            shard = self.shards[s]
            with shard.lock:       # a concurrent pull iterates the table
                for k, v in items:
                    shard.table[k] = v
        return int(len(ids))

    def ids(self) -> np.ndarray:
        """All resident row ids (sorted) — the leak/rebalance audit
        surface: after a resize, the union across servers must equal the
        pre-resize union exactly (nothing leaked, nothing duplicated)."""
        out = []
        for s in self.shards:
            with s.lock:
                out.extend(s.table.keys())
        return np.sort(np.asarray(out, np.int64))


class SparseEmbedding:
    """Trainer-side helper: pull rows for a batch of ids into a dense
    [N, dim] device array, and push grads back after the step — the
    DownpourWorker per-batch flow (downpour_worker.cc) as two calls."""

    def __init__(self, kv: LargeScaleKV):
        self.kv = kv
        self._last_ids: Optional[np.ndarray] = None

    def pull(self, ids):
        import jax.numpy as jnp

        ids = np.asarray(ids, np.int64)
        self._last_ids = ids.reshape(-1)
        rows = self.kv.pull(self._last_ids)
        return jnp.asarray(rows.reshape(ids.shape + (self.kv.dim,)))

    def push(self, grads, lr: float = 0.01):
        assert self._last_ids is not None, "push before pull"
        self.kv.push(self._last_ids, np.asarray(grads).reshape(
            len(self._last_ids), self.kv.dim), lr)
