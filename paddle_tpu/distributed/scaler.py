"""Scale-event protocol: signal-driven world-size policy.

Capability mirror of the reference's elastic story
(`DistributedStrategy.elastic`, the Fleet heartbeat/elastic surfaces):
the reference reserves a flag and leaves the control loop to an external
operator; here the control loop is in-tree. A ``ScalerPolicy`` reads the
LIVE evidence the rest of the stack already publishes — heartbeat
verdicts (``ps.trainer_dead`` / ``ps.trainer_revived`` /
``ps.barrier_regrown``), queue saturation (serving admission depth or
the PR 16 fleet view's ``fleet.queue_frac``), step-time p99 over the
rolling window, router load — and emits typed ScaleUp/ScaleDown
decisions with cooldowns and min/max bounds.

The policy only DECIDES. Execution belongs to the callers:

* ``ElasticRunner`` (distributed/elastic.py) executes a training-world
  decision as checkpoint → barrier-drain → relaunch-at-new-world;
* ``ClusterController.scale_to`` (serving/cluster.py) grows/shrinks the
  serving replica set through the drain/ready state machine;
* ``tools/chaos_check.py --resize`` drives both through injected chaos.

Every decision is counted (``scaler.evaluations``, ``scaler.decisions``,
``scaler.scale_up``, ``scaler.scale_down``, ``scaler.suppressed_cooldown``,
``scaler.clamped``) and every EXECUTED transition lands in the incident
ring as a ``kind:"scale"`` record (core/incidents.report_scale_event).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..core import flags as _flags
from ..core import telemetry

SCALE_UP = "up"
SCALE_DOWN = "down"


@dataclass(frozen=True)
class ScaleDecision:
    """One typed verdict: move the world from ``current`` to ``target``.

    ``reason`` names the rule that fired (heartbeat_dead,
    worker_rejoined, queue_saturation, step_time_p99, underutilized);
    ``signals`` carries the evidence snapshot the rule judged."""

    direction: str                 # SCALE_UP | SCALE_DOWN
    current: int
    target: int
    reason: str
    signals: Dict[str, Any] = field(default_factory=dict)
    ts: float = 0.0

    @property
    def delta(self) -> int:
        return self.target - self.current


@dataclass
class ScaleSignals:
    """The evidence vector a policy judges — normalised from whatever
    plane produced it (training PS world, serving fleet, local
    telemetry window) so one policy serves both planes."""

    dead_workers: int = 0          # heartbeat verdicts in the window
    joined_workers: int = 0        # revived/announced workers in window
    queue_frac: float = 0.0        # queue depth / admission bound, 0..1
    queue_evidence: bool = False   # the window actually saw traffic
    step_p99_ms: float = 0.0       # step-time p99 over the window
    extra: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        d = {"dead_workers": self.dead_workers,
             "joined_workers": self.joined_workers,
             "queue_frac": round(float(self.queue_frac), 4),
             "queue_evidence": bool(self.queue_evidence),
             "step_p99_ms": round(float(self.step_p99_ms), 3)}
        d.update(self.extra)
        return d


def gather_signals(window: Optional[Dict[str, Any]] = None,
                   fleet=None,
                   window_s: Optional[float] = None,
                   now: Optional[float] = None) -> ScaleSignals:
    """Build a ScaleSignals from the live telemetry window (and the
    fleet observatory when one is attached). ``window`` is injectable
    for deterministic tests; by default the rolling
    ``telemetry.windowed(FLAGS_scaler_window_s)`` view is read."""
    if window is None:
        W = float(window_s if window_s is not None
                  else _flags.flag("scaler_window_s"))
        window = telemetry.windowed(W, now=now)
    counters = window.get("counters") or {}
    hists = window.get("hists") or {}
    gauges = window.get("gauges") or {}

    def cdelta(name: str) -> float:
        rec = counters.get(name) or {}
        try:
            return float(rec.get("delta") or 0)
        except (TypeError, ValueError):
            return 0.0

    dead = cdelta("ps.trainer_dead")
    revived = cdelta("ps.trainer_revived")
    joined = cdelta("ps.barrier_regrown")
    sig = ScaleSignals(
        dead_workers=max(0, int(dead - revived)),
        joined_workers=int(max(revived, joined)))
    # queue saturation: prefer the fleet-merged view, fall back to the
    # local serving gauge against the admission bound
    qf = None
    if fleet is not None:
        try:
            qf = ((fleet.status() or {}).get("fleet")
                  or {}).get("queue_frac")
        except Exception:
            qf = None
    if qf is None:
        qf = gauges.get("fleet.queue_frac")
    if qf is None:
        depth = gauges.get("serving.queue_depth")
        bound = float(_flags.flag("serving_max_queue_depth") or 0)
        if depth is not None and bound > 0:
            qf = float(depth) / bound
    if qf is not None:
        sig.queue_frac = max(0.0, float(qf))
        sig.queue_evidence = True
    # step-time p99 over the window: first step-latency histogram wins
    for hname in ("executor.run_steps_ms", "executor.run_ms",
                  "serving.request_ms"):
        h = hists.get(hname)
        if h and h.get("count"):
            sig.step_p99_ms = float(h.get("p99") or 0.0)
            sig.extra["step_metric"] = hname
            break
    return sig


class ScalerPolicy:
    """Cooldown-gated, bound-clamped scale policy over ScaleSignals.

    Rule order (first hit wins):
      1. dead_workers > 0           → ScaleDown to the survivor count
      2. joined_workers > 0         → ScaleUp (re-absorb the announced
                                      worker — the barrier-regrow path)
      3. queue_frac ≥ high          → ScaleUp   (queue_saturation)
      4. step_p99 ≥ bound (if set)  → ScaleUp   (step_time_p99)
      5. queue_frac ≤ low w/traffic → ScaleDown (underutilized)

    A decision outside [min_world, max_world] clamps; a clamp that
    lands back on the current world is suppressed (scaler.clamped).
    A decision inside the cooldown since the last one is suppressed
    (scaler.suppressed_cooldown) — the thrash guard.
    """

    def __init__(self, min_world: Optional[int] = None,
                 max_world: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 queue_high_frac: Optional[float] = None,
                 queue_low_frac: Optional[float] = None,
                 step_p99_high_ms: Optional[float] = None,
                 step: int = 1, source: str = "scaler"):
        f = _flags.flag
        self.min_world = int(f("scaler_min_world") if min_world is None
                             else min_world)
        self.max_world = int(f("scaler_max_world") if max_world is None
                             else max_world)
        if self.min_world < 1 or self.max_world < self.min_world:
            raise ValueError(
                f"ScalerPolicy: need 1 <= min_world <= max_world, got "
                f"[{self.min_world}, {self.max_world}]")
        self.cooldown_s = float(f("scaler_cooldown_s") if cooldown_s is None
                                else cooldown_s)
        self.queue_high = float(f("scaler_queue_high_frac")
                                if queue_high_frac is None
                                else queue_high_frac)
        self.queue_low = float(f("scaler_queue_low_frac")
                               if queue_low_frac is None
                               else queue_low_frac)
        self.step_p99_high = float(f("scaler_step_p99_high_ms")
                                   if step_p99_high_ms is None
                                   else step_p99_high_ms)
        self.step = max(1, int(step))
        self.source = source
        self._last_decision_ts: Optional[float] = None

    # -- the rules -----------------------------------------------------------
    def _judge(self, world: int, sig: ScaleSignals):
        """(direction, raw_target, reason) or None — bounds/cooldown are
        applied by decide(), not here."""
        if sig.dead_workers > 0:
            return (SCALE_DOWN, world - sig.dead_workers, "heartbeat_dead")
        if sig.joined_workers > 0:
            return (SCALE_UP, world + sig.joined_workers, "worker_rejoined")
        if sig.queue_evidence and sig.queue_frac >= self.queue_high:
            return (SCALE_UP, world + self.step, "queue_saturation")
        if self.step_p99_high > 0 and sig.step_p99_ms >= self.step_p99_high:
            return (SCALE_UP, world + self.step, "step_time_p99")
        if sig.queue_evidence and sig.queue_frac <= self.queue_low:
            return (SCALE_DOWN, world - self.step, "underutilized")
        return None

    def decide(self, world: int, signals: Optional[ScaleSignals] = None,
               now: Optional[float] = None,
               fleet=None) -> Optional[ScaleDecision]:
        """Judge the current evidence; returns a ScaleDecision or None.

        The returned decision is already clamped to [min_world,
        max_world] and has passed the cooldown gate — a non-None return
        is safe to execute."""
        if now is None:
            now = time.time()
        if signals is None:
            signals = gather_signals(fleet=fleet, now=now)
        telemetry.counter_add("scaler.evaluations", 1, source=self.source)
        verdict = self._judge(int(world), signals)
        if verdict is None:
            return None
        direction, target, reason = verdict
        clamped = min(self.max_world, max(self.min_world, int(target)))
        if clamped != target:
            telemetry.counter_add("scaler.clamped", 1, source=self.source,
                                  reason=reason, target=int(target),
                                  clamped=clamped)
            target = clamped
        if target == int(world):
            return None                 # fully clamped away
        if self._last_decision_ts is not None and \
                now - self._last_decision_ts < self.cooldown_s:
            telemetry.counter_add("scaler.suppressed_cooldown", 1,
                                  source=self.source, reason=reason)
            return None
        self._last_decision_ts = now
        decision = ScaleDecision(direction=direction, current=int(world),
                                 target=int(target), reason=reason,
                                 signals=signals.as_dict(), ts=now)
        telemetry.counter_add("scaler.decisions", 1, source=self.source,
                              reason=reason, direction=direction,
                              current=decision.current,
                              target=decision.target)
        if direction == SCALE_UP:
            telemetry.counter_add("scaler.scale_up", 1,
                                  source=self.source, reason=reason)
        else:
            telemetry.counter_add("scaler.scale_down", 1,
                                  source=self.source, reason=reason)
        return decision

    def reset_cooldown(self):
        self._last_decision_ts = None

    @classmethod
    def from_slo_rules(cls, up_rules=None, down_rules=None,
                       **kw) -> "SLOScalerPolicy":
        """A policy whose evidence is the incident plane's FIRING state
        instead of raw metrics: the PR 18 watchdog already applies
        windowing, min-samples and warmup baselines before latching a
        ``slo.<rule>_firing`` gauge, so the scaler reuses that verdict
        rather than re-deriving it from the same counters.

        ``up_rules`` / ``down_rules`` name the SLO rules (incidents.Rule
        names, e.g. the built-in ``decode_queue_saturation``) whose
        firing argues ScaleUp / ScaleDown. Cooldown/bounds/step keyword
        arguments pass through to :class:`ScalerPolicy` unchanged."""
        return SLOScalerPolicy(
            up_rules=_SLO_UP_DEFAULT if up_rules is None else up_rules,
            down_rules=(_SLO_DOWN_DEFAULT if down_rules is None
                        else down_rules), **kw)


# SLO rules whose firing is capacity evidence. Saturated admission
# queues, regressed step time and router failover bursts all argue MORE
# replicas; a live-MFU collapse on an otherwise healthy world argues the
# fleet is over-provisioned for the work it is getting.
_SLO_UP_DEFAULT = ("decode_queue_saturation", "serving_queue_saturation",
                   "step_time_p99", "router_failover_burst")
_SLO_DOWN_DEFAULT = ("live_mfu_drop",)


class SLOScalerPolicy(ScalerPolicy):
    """ScalerPolicy driven by ``slo.<rule>_firing`` gauges (build via
    :meth:`ScalerPolicy.from_slo_rules`). Rule order: first firing
    up-rule wins, then first firing down-rule; the base class still owns
    clamping and the cooldown gate, so one sustained queue-saturation
    episode yields exactly ONE ScaleUp per cooldown window."""

    def __init__(self, up_rules=(), down_rules=(), source: str = "slo",
                 **kw):
        super().__init__(source=source, **kw)
        self.up_rules = tuple(str(r) for r in up_rules)
        self.down_rules = tuple(str(r) for r in down_rules)

    def firing_rules(self) -> list:
        """Rule names currently latched firing (gauge value truthy)."""
        gauges = telemetry.gauges()
        out = []
        for name in self.up_rules + self.down_rules:
            if gauges.get(f"slo.{name}_firing"):
                out.append(name)
        return out

    def _judge(self, world: int, sig: ScaleSignals):
        firing = sig.extra.get("slo_firing")
        if firing is None:
            firing = self.firing_rules()
            sig.extra["slo_firing"] = sorted(firing)
        for name in self.up_rules:
            if name in firing:
                return (SCALE_UP, world + self.step, name)
        for name in self.down_rules:
            if name in firing:
                return (SCALE_DOWN, world - self.step, name)
        return None


class ResizeSchedule:
    """Deterministic step-triggered resize plan for the launch.py
    orchestrator: ``"step:world,step:world"`` (e.g. ``"4:3,8:2"`` —
    grow to 3 trainers once any trainer reports step 4, shrink back to
    2 at step 8). Entries fire once each, in step order; the
    orchestrator polls :meth:`next_target` with the max observed
    trainer step between supervision passes. Malformed specs raise at
    parse time — a silently-dropped resize plan is worse than a loud
    one."""

    def __init__(self, spec: str = "",
                 entries: Optional[list] = None):
        plan = []
        if entries is not None:
            plan = [(int(s), int(w)) for s, w in entries]
        else:
            for part in str(spec or "").split(","):
                part = part.strip()
                if not part:
                    continue
                step_s, sep, world_s = part.partition(":")
                if not sep:
                    raise ValueError(
                        f"ResizeSchedule: entry {part!r} is not "
                        f"'step:world'")
                plan.append((int(step_s), int(world_s)))
        for _, world in plan:
            if world < 1:
                raise ValueError("ResizeSchedule: world must be >= 1")
        self._plan = sorted(plan)
        self.executed: list = []

    def pending(self) -> list:
        return list(self._plan)

    def next_target(self, step: int) -> Optional[int]:
        """World size to resize to once ``step`` has been reached, or
        None. Consumes every entry whose trigger step has passed and
        returns the LAST one — a supervisor that stalled past two
        triggers jumps straight to the final world."""
        target = None
        while self._plan and step >= self._plan[0][0]:
            entry = self._plan.pop(0)
            self.executed.append(entry)
            target = entry[1]
        return target
