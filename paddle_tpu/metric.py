"""Metrics (reference: python/paddle/metric/metrics.py + fluid/metrics.py).

Streaming metrics with the 2.0 protocol: ``compute`` (optional per-batch
tensor prep), ``update`` (numpy accumulation on host), ``accumulate``,
``reset``, ``name``. Used standalone or via hapi ``Model.prepare(metrics=…)``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _np(x) -> np.ndarray:
    if hasattr(x, "numpy"):
        return x.numpy()
    return np.asarray(x)


class Metric:
    def __init__(self, name: Optional[str] = None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self) -> Union[str, List[str]]:
        return self._name

    def compute(self, pred, label, *args):
        """Per-batch hook run in the graph/dygraph context; default
        passthrough. Subclasses may return derived tensors that `update`
        then consumes as numpy."""
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy (reference: metric/metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = tuple(topk) if isinstance(topk, (list, tuple)) else (topk,)
        super().__init__(name or "acc")
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        return pred, label

    def update(self, pred, label, *args):
        pred, label = _np(pred), _np(label)
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]
        maxk = max(self.topk)
        top = np.argsort(-pred, axis=-1)[..., :maxk]
        correct = top == label[..., None]
        n = label.size
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(-1).sum()
            self.count[i] += n
        return self.accumulate()

    def accumulate(self):
        acc = [t / max(c, 1.0) for t, c in zip(self.total, self.count)]
        return acc[0] if len(acc) == 1 else acc

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision: TP / (TP + FP); pred is P(y=1) (reference:
    metric/metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, pred, label, *args):
        pred, label = _np(pred).reshape(-1), _np(label).reshape(-1)
        hard = (pred > 0.5).astype(np.int64)
        self.tp += int(((hard == 1) & (label == 1)).sum())
        self.fp += int(((hard == 1) & (label == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    """Binary recall: TP / (TP + FN)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, pred, label, *args):
        pred, label = _np(pred).reshape(-1), _np(label).reshape(-1)
        hard = (pred > 0.5).astype(np.int64)
        self.tp += int(((hard == 1) & (label == 1)).sum())
        self.fn += int(((hard == 0) & (label == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    """ROC AUC via threshold bucketing (reference: metric/metrics.py Auc /
    operators/metrics/auc_op — same bucketed estimator)."""

    def __init__(self, curve: str = "ROC", num_thresholds: int = 4095,
                 name=None):
        self.num_thresholds = num_thresholds
        super().__init__(name)
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels, *args):
        preds, labels = _np(preds), _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        idx = np.clip((pos_prob * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        pos = labels != 0
        np.add.at(self._stat_pos, idx[pos], 1)
        np.add.at(self._stat_neg, idx[~pos], 1)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            p, n = self._stat_pos[i], self._stat_neg[i]
            # trapezoid over the (fp, tp) staircase
            auc += n * (tot_pos + tot_pos + p) / 2.0
            tot_pos += p
            tot_neg += n
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)
