"""Dataset API over the native data runtime.

Capability mirror of python/paddle/fluid/dataset.py (DatasetFactory:23,
InMemoryDataset:329 load_into_memory:661 global_shuffle:746,
QueueDataset:923) backed by the C++ MultiSlot engine (native/data_feed.cc —
the reference's data_feed.cc/data_set.cc). Falls back to a pure-Python
parser when no toolchain is available, same API.

Slots are declared via set_use_var(program_vars): dtype int64 → 'u'
(uint64 ids), float32 → 'f'. Dense vars (lod_level 0) are reshaped to
[rows] + var.shape[1:]; lod vars yield (values, lod_offsets).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class _PyParserDataset:
    """Pure-Python fallback with the NativeDataset interface."""

    def __init__(self, slots):
        self.slots = list(slots)
        self._records: List[List[np.ndarray]] = []
        self._files: List[str] = []

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self, num_threads: int = 1) -> int:
        self._records = []      # reload replaces, never duplicates
        for path in self._files:
            with open(path) as f:
                for line in f:
                    toks = line.split()
                    if not toks:
                        continue
                    rec = []
                    pos = 0
                    for name, typ in self.slots:
                        if pos >= len(toks):
                            raise ValueError(
                                f"{path}: truncated line, missing slot "
                                f"'{name}'")
                        n = int(toks[pos])
                        pos += 1
                        vals = toks[pos:pos + n]
                        if len(vals) != n:
                            raise ValueError(
                                f"{path}: slot '{name}' declares {n} values "
                                f"but line has {len(vals)}")
                        pos += n
                        rec.append(np.asarray(
                            vals, dtype=np.float32 if typ == "f" else np.int64))
                    self._records.append(rec)
        return len(self._records)

    def global_shuffle(self, seed: int = 0):
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
        order = rng.permutation(len(self._records))
        self._records = [self._records[i] for i in order]

    def num_records(self) -> int:
        return len(self._records)

    def batches(self, batch_size: int):
        for start in range(0, len(self._records), batch_size):
            chunk = self._records[start:start + batch_size]
            out = {}
            for idx, (name, typ) in enumerate(self.slots):
                vals = np.concatenate([r[idx] for r in chunk]) if chunk else \
                    np.zeros((0,), np.float32 if typ == "f" else np.int64)
                lod = np.cumsum([0] + [len(r[idx]) for r in chunk]).astype(
                    np.int64)
                out[name] = (vals, lod)
            yield out


class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 4
        self.filelist: List[str] = []
        self.use_vars: List[Any] = []
        self._engine = None
        self._force_python = False

    # -- reference API ---------------------------------------------------------
    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist: Sequence[str]):
        self.filelist = list(filelist)
        if self._engine is not None:
            self._engine.set_filelist(self.filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_pipe_command(self, cmd: str):
        # reference pipes raw data through a user command (data_feed.proto);
        # preprocessing belongs upstream here — kept for API parity
        self._pipe_command = cmd

    # -- engine ---------------------------------------------------------------
    def _slots(self):
        if not self.use_vars:
            raise ValueError("call set_use_var(vars) before loading data")
        slots = []
        for v in self.use_vars:
            typ = "u" if "int" in str(v.dtype) else "f"
            slots.append((v.name, typ))
        return slots

    def _ensure_engine(self):
        if self._engine is None:
            from . import native

            if not self._force_python and native.available():
                self._engine = native.NativeDataset(self._slots())
            else:
                self._engine = _PyParserDataset(self._slots())
            if self.filelist:
                self._engine.set_filelist(self.filelist)
        return self._engine

    def _dense_shape(self, var):
        return [int(d) for d in (var.shape[1:] if var.shape else [])]

    def _feed_from_raw(self, raw) -> Dict[str, Any]:
        feed: Dict[str, Any] = {}
        for v in self.use_vars:
            vals, lod = raw[v.name]
            if getattr(v, "lod_level", 0):
                feed[v.name] = (vals, lod)
            else:
                tail = self._dense_shape(v)
                rows = len(lod) - 1
                feed[v.name] = vals.reshape([rows] + tail)
        return feed

    def iter_batches(self):
        """Yield feed dicts {var_name: ndarray} (dense vars reshaped; lod
        vars yield (values, lod) tuples)."""
        engine = self._ensure_engine()
        for raw in engine.batches(self.batch_size):
            yield self._feed_from_raw(raw)


class InMemoryDataset(DatasetBase):
    """reference: dataset.py:329."""

    def load_into_memory(self):
        return self._ensure_engine().load_into_memory(self.thread_num)

    def global_shuffle(self, fleet=None, thread_num: Optional[int] = None,
                       seed: int = 0):
        self._ensure_engine().global_shuffle(seed)

    def get_memory_data_size(self, fleet=None) -> int:
        return self._ensure_engine().num_records()

    def release_memory(self):
        self._engine = None


class QueueDataset(DatasetBase):
    """reference: dataset.py:923 — streaming reader: native parser threads
    feed a bounded channel, batches stream out without materialising the
    dataset in memory (falls back to load-then-iterate on the pure-Python
    engine)."""

    def iter_batches(self):
        engine = self._ensure_engine()
        if hasattr(engine, "stream_batches"):
            raw_iter = engine.stream_batches(self.batch_size,
                                             self.thread_num)
        else:
            if engine.num_records() == 0:
                engine.load_into_memory(self.thread_num)
            raw_iter = engine.batches(self.batch_size)
        for raw in raw_iter:
            yield self._feed_from_raw(raw)


class DatasetFactory:
    """reference: dataset.py:23."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")
