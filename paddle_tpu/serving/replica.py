"""Replica runner — one ServingEngine process of the cluster fleet.

``python -m paddle_tpu.serving.replica --model-root DIR`` (or
``--model-dir`` for a bare inference-model dir) builds the predictor
from the newest VERIFIED published model (checkpoint.ModelWatcher),
binds the PR 4 HTTP server FIRST — so the controller can poll
``/healthz`` and watch readiness go ``starting`` → ``ok`` as warmup
finishes — then warms every bucket and serves until told to stop.

The process announces itself on stdout with one machine-readable line::

    PT_REPLICA_READY {"url": ..., "port": ..., "pid": ..., "version": ...}

which is the only contract serving/cluster.py parses (everything after
it is ordinary logging). Model swaps arrive over ``POST /v1/admin/swap``
from the controller's rolling-swap driver; ``--poll-s`` > 0 instead arms
a SELF-watching loop for routerless single-replica deployments.
SIGTERM/SIGINT drain the queue and exit 0 — the controller's graceful
stop; anything harder (SIGKILL, the chaos gate's weapon) is exactly the
crash the router's failover exists for.

Fault injection: the process inherits PT_FAULT_SPEC / PT_FAULT_SEED from
its environment, so a chaos run arms ``serving.handler`` /
``replica.swap`` in every replica without code changes.

``--decode-model-dir`` instead runs a GENERATIVE replica: the
continuous-batching decode engine (serving/decode.py) over a
models/decoder_lm servable dir, same announce/drain contract, serving
``POST /v1/generate`` (``decode.step`` / ``decode.kv_alloc`` fault
sites armed the same way).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from typing import Optional


def run_decode_replica(args) -> int:
    """--decode-model-dir mode: one GENERATIVE replica (DecodeEngine
    over a models/decoder_lm servable dir) behind the same HTTP surface
    and PT_REPLICA_READY / SIGTERM-drain contract — POST /v1/generate
    instead of /v1/infer."""
    from ..core import telemetry
    from .decode import decode_engine_from_dir
    from .server import ServingHTTPServer

    if args.telemetry_log:
        telemetry.configure(args.telemetry_log)
    config = None
    if args.role != "unified" or args.prefill_urls or args.prefix_cache:
        from .decode import DecodeConfig

        config = DecodeConfig(role=args.role,
                              prefill_urls=args.prefill_urls,
                              prefix_cache=args.prefix_cache or None)
    engine = decode_engine_from_dir(args.decode_model_dir, config=config)
    if args.journal_url:
        # session-failover journal (serving/session.py): replicate
        # snapshots to the router at step-boundary cadence. Short
        # timeout + swallowed errors — a slow router must never stall
        # the decode step; the engine counts session.journal_errors.
        import http.client as _hc
        import urllib.parse as _up

        u = _up.urlparse(args.journal_url)

        def _journal_sink(records, _host=u.hostname, _port=u.port,
                          _path=(u.path or "/v1/session/journal")):
            conn = _hc.HTTPConnection(_host, _port, timeout=2.0)
            try:
                conn.request("POST", _path,
                             body=json.dumps({"records": records}).encode(),
                             headers={"Content-Type": "application/json"})
                conn.getresponse().read()
            finally:
                conn.close()

        engine.journal_sink = _journal_sink
    server = ServingHTTPServer(None, host=args.host, port=args.port,
                               decode_engine=engine).start()
    print("PT_REPLICA_READY " + json.dumps(
        {"url": server.url, "port": server.port, "pid": os.getpid(),
         "version": engine.version, "model_dir": args.decode_model_dir,
         "decode": True, "role": engine.config.role}), flush=True)

    stop = threading.Event()

    def _graceful(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    engine.start(warmup=not args.no_warmup)
    try:
        stop.wait()
    finally:
        engine.close(drain=True, timeout=30)
        server.shutdown()
        telemetry.flush_sink()
    return 0


def run_replica(args) -> int:
    from .. import checkpoint as ckpt
    from ..core import telemetry
    from ..inference import AnalysisConfig, create_predictor
    from .engine import ServingConfig, ServingEngine
    from .server import ServingHTTPServer

    if args.telemetry_log:
        telemetry.configure(args.telemetry_log)

    version = 0
    watcher: Optional[ckpt.ModelWatcher] = None
    if args.model_root:
        watcher = ckpt.ModelWatcher(args.model_root)
        newest = watcher.poll()
        if newest is None:
            print(f"PT_REPLICA_FAIL no verified published model under "
                  f"{args.model_root}", flush=True)
            return 2
        version, model_dir = newest
    else:
        model_dir = args.model_dir

    cfg = ServingConfig(
        max_batch_size=args.max_batch_size or None,
        batch_timeout_ms=args.batch_timeout_ms
        if args.batch_timeout_ms >= 0 else None)
    engine = ServingEngine(create_predictor(AnalysisConfig(model_dir)),
                           config=cfg, version=version)
    server = ServingHTTPServer(engine, host=args.host,
                               port=args.port).start()
    # announce BEFORE warmup: the controller learns the port immediately
    # and watches /healthz flip from "starting" to "ok" when warm
    print("PT_REPLICA_READY " + json.dumps(
        {"url": server.url, "port": server.port, "pid": os.getpid(),
         "version": version, "model_dir": model_dir}), flush=True)

    stop = threading.Event()

    def _graceful(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    engine.start(warmup=not args.no_warmup)

    try:
        while not stop.wait(args.poll_s if args.poll_s > 0 else 1.0):
            if watcher is not None and args.poll_s > 0:
                # self-watching mode (no controller): swap in place when a
                # newer verified version lands
                newest = watcher.poll()
                if newest is not None:
                    v, path = newest
                    try:
                        pred = create_predictor(AnalysisConfig(path))
                        engine.swap_predictor(pred, version=v)
                        print(f"PT_REPLICA_SWAPPED {v}", flush=True)
                    except Exception as e:
                        print(f"PT_REPLICA_SWAP_FAIL {v} {e!r}", flush=True)
    finally:
        engine.close(drain=True, timeout=30)
        server.shutdown()
        telemetry.flush_sink()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="one serving replica process (cluster.py launches "
                    "these; standalone use works too)")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-root",
                     help="published-models root (checkpoint.publish_model "
                          "layout); serves the newest VERIFIED version")
    src.add_argument("--model-dir",
                     help="bare inference-model dir (io.save_inference_"
                          "model layout), served as version 0")
    src.add_argument("--decode-model-dir",
                     help="decoder-LM servable dir (models/decoder_lm."
                          "save_decoder_lm layout): run a GENERATIVE "
                          "replica — POST /v1/generate via the "
                          "continuous-batching decode engine")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 binds an ephemeral port (announced on stdout)")
    ap.add_argument("--max-batch-size", type=int, default=0,
                    help="0 = FLAGS_serving_max_batch_size")
    ap.add_argument("--batch-timeout-ms", type=float, default=-1.0,
                    help="< 0 = FLAGS_serving_batch_timeout_ms")
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--role", default="unified",
                    choices=("unified", "prefill", "decode"),
                    help="disaggregated-serving tier of a decode replica "
                         "(serving/disagg.py): 'prefill' ships KV pages "
                         "over POST /v1/prefill, 'decode' installs them, "
                         "'unified' does both locally")
    ap.add_argument("--prefill-urls", default="",
                    help="comma-separated prefill-tier URLs a decode-role "
                         "replica fetches KV shipments from")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable the content-addressed prefix store "
                         "(serving/prefix_store.py) on this replica")
    ap.add_argument("--journal-url", default="",
                    help="router endpoint decode replicas replicate "
                         "session-failover journals to (serving/"
                         "session.py) — usually ROUTER_URL/v1/session/"
                         "journal; empty disables journaling")
    ap.add_argument("--poll-s", type=float, default=0.0,
                    help="> 0 arms SELF-watching of --model-root for new "
                         "versions (routerless mode); the cluster "
                         "controller leaves this 0 and drives swaps over "
                         "/v1/admin/swap")
    ap.add_argument("--telemetry-log", default="",
                    help="JSONL run log for this replica (one file per "
                         "process; tools/trace_view.py merges them)")
    args = ap.parse_args(argv)
    if args.decode_model_dir:
        return run_decode_replica(args)
    return run_replica(args)


if __name__ == "__main__":
    sys.exit(main())
