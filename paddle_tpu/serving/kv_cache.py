"""Paged KV cache — the decode engine's preallocated page pool.

vLLM's PagedAttention memory discipline in dense-jax form: instead of
one max-length KV buffer per request (whose worst case is what forces
tiny batch sizes), the engine preallocates ONE pool of fixed-size pages
per layer and hands each request just the pages its sequence actually
needs. Pages are allocated at admission (worst case for the request:
ceil((prompt + max_new_tokens) / page_size), so a mid-generation
allocation can never fail) and freed the moment the request retires —
continuous batching churns requests through the same arrays with no
device alloc/free traffic at all.

Page 0 is a reserved scratch page: the ops route padded prompt
positions and empty decode slots there (see ops/attention_ops.py
kv_cache_write / cached_kv_attention), so a masked write can never
touch a page owned by a live request.

Accounting: the pool's bytes book into the PR 10 HBM ledger as
``mem.serving.kv_pool_bytes`` (preallocated, the resident figure),
``mem.serving.kv_used_bytes`` (pages currently owned by live requests)
and ``mem.serving.kv_high_water_bytes`` — rendered by tools/mem_report
and /v1/stats, and what lets admission refuse a request that would OOM
(typed ``KVCacheExhaustedError``) instead of dying mid-decode.
``decode.kv_alloc`` is a fault-injection site (core/faults.py,
tools/chaos_check.py --decode).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..core import costmodel, faults, telemetry
from ..core.analysis import lockdep
from .admission import KVCacheExhaustedError


class KVPagePool:
    """Free-list allocator over preallocated per-layer page arrays.

    The jax arrays themselves (``pools``: kv_k_<l>/kv_v_<l> ->
    [num_pages, page_size, kv_dim]) are owned and threaded/donated by
    the engine's step function; this object owns the PAGE IDS and the
    ledger accounting. Page 0 is never handed out."""

    def __init__(self, n_layers: int, num_pages: int, page_size: int,
                 kv_dim: int, dtype: str = "float32"):
        if num_pages < 2:
            raise ValueError(f"KV pool needs >= 2 pages (page 0 is the "
                             f"reserved scratch page), got {num_pages}")
        self.n_layers = int(n_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.kv_dim = int(kv_dim)
        self.dtype = dtype
        self._lock = lockdep.lock("serving.kv_pool")
        self._free: List[int] = list(range(1, self.num_pages))
        self._lent: set = set()
        self._high_water_pages = 0
        import numpy as np

        itemsize = np.dtype(dtype).itemsize
        # keys + values, every layer
        self.pool_bytes = (2 * self.n_layers * self.num_pages *
                           self.page_size * self.kv_dim * itemsize)
        self._page_bytes = self.pool_bytes // self.num_pages
        telemetry.gauge_set("mem.serving.kv_pool_bytes", self.pool_bytes)
        telemetry.gauge_set("mem.serving.kv_used_bytes", 0)
        telemetry.gauge_set("mem.serving.kv_high_water_bytes", 0)
        costmodel.refresh_ledger()

    def make_arrays(self) -> Dict[str, Any]:
        """Fresh zeroed device pools keyed by the program feed names."""
        import jax.numpy as jnp

        shape = (self.num_pages, self.page_size, self.kv_dim)
        out = {}
        for i in range(self.n_layers):
            out[f"kv_k_{i}"] = jnp.zeros(shape, self.dtype)
            out[f"kv_v_{i}"] = jnp.zeros(shape, self.dtype)
        return out

    # -- capacity ------------------------------------------------------------
    @property
    def capacity_pages(self) -> int:
        """Allocatable pages (page 0 excluded)."""
        return self.num_pages - 1

    def pages_for_tokens(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def check_fits(self, tokens: int):
        """Typed admission-time refusal: a request whose WORST-CASE page
        need exceeds the whole pool can never be served — refuse it now
        instead of letting it OOM the cache mid-generation."""
        need = self.pages_for_tokens(tokens)
        if need > self.capacity_pages:
            telemetry.counter_add("decode.kv_refusals", 1, pages=need)
            raise KVCacheExhaustedError(
                f"request needs {need} KV pages ({tokens} tokens at "
                f"{self.page_size}/page) but the pool holds "
                f"{self.capacity_pages} — over the KV budget "
                f"(mem.serving.kv_pool_bytes={self.pool_bytes}); raise "
                f"FLAGS_decode_kv_pages or shorten the request")
        return need

    # -- alloc / free --------------------------------------------------------
    def try_alloc(self, n: int) -> List[int]:
        """Pop n pages, or [] when the pool cannot seat them right now
        (the request stays queued until retirements free pages).
        ``decode.kv_alloc`` faults inject here."""
        faults.maybe_fail("decode.kv_alloc", pages=n)
        with self._lock:
            if n > len(self._free):
                return []
            pages = self._free[:n]
            del self._free[:n]
            self._lent.update(pages)
            used = self.capacity_pages - len(self._free)
            self._high_water_pages = max(self._high_water_pages, used)
            hw = self._high_water_pages
        telemetry.counter_add("decode.kv_pages_allocated", n)
        telemetry.gauge_set("mem.serving.kv_used_bytes",
                            used * self._page_bytes)
        telemetry.gauge_set("mem.serving.kv_high_water_bytes",
                            hw * self._page_bytes)
        return pages

    def free(self, pages: List[int]):
        if not pages:
            return
        with self._lock:
            dup = set(pages) & set(self._free)
            if dup or 0 in pages:
                raise AssertionError(
                    f"KV pool corruption: freeing pages {sorted(dup)} "
                    f"already free (or the reserved page 0)")
            self._free.extend(pages)
            self._lent.difference_update(pages)
            used = self.capacity_pages - len(self._free)
        telemetry.counter_add("decode.kv_pages_freed", len(pages))
        telemetry.gauge_set("mem.serving.kv_used_bytes",
                            used * self._page_bytes)

    # -- invariants ----------------------------------------------------------
    def audit(self, owned: List[int] = None) -> List[str]:
        """Invariant check: the free list and the lent set must PARTITION
        pages 1..num_pages-1 — disjoint, no duplicates, page 0 never
        handed out. With ``owned`` (every page id the callers believe
        they hold: request-private pages + prefix-store pages), also
        checks lent == owned, i.e. no leaked and no over-freed pages.
        Returns a list of violation strings (empty = clean) and counts
        each failing call as ``kv.audit_failures`` — the chaos_check
        --prefix / --decode gate and tests/test_prefix_store.py assert
        on this."""
        problems: List[str] = []
        with self._lock:
            free = list(self._free)
            lent = set(self._lent)
        if len(free) != len(set(free)):
            problems.append("duplicate pages on the free list")
        if 0 in free or 0 in lent:
            problems.append("reserved page 0 entered circulation")
        overlap = set(free) & lent
        if overlap:
            problems.append(f"pages both free and lent: {sorted(overlap)}")
        universe = set(range(1, self.num_pages))
        missing = universe - set(free) - lent
        if missing:
            problems.append(f"pages vanished from the pool: "
                            f"{sorted(missing)}")
        extra = (set(free) | lent) - universe
        if extra:
            problems.append(f"pages outside the pool: {sorted(extra)}")
        if owned is not None:
            owned_set = set(owned)
            if len(owned) != len(owned_set):
                problems.append("a page is owned twice")
            leaked = lent - owned_set
            if leaked:
                problems.append(f"leaked pages (lent but unowned): "
                                f"{sorted(leaked)}")
            stale = owned_set - lent
            if stale:
                problems.append(f"over-freed pages (owned but not "
                                f"lent): {sorted(stale)}")
        if problems:
            telemetry.counter_add("kv.audit_failures", 1)
        return problems

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            free = len(self._free)
            hw = self._high_water_pages
        return {"page_size": self.page_size,
                "pages_total": self.capacity_pages,
                "pages_free": free,
                "pages_used": self.capacity_pages - free,
                "high_water_pages": hw,
                "pool_bytes": self.pool_bytes,
                "used_bytes": (self.capacity_pages - free) *
                self._page_bytes,
                "high_water_bytes": hw * self._page_bytes}
