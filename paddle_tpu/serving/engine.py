"""ServingEngine — dynamic micro-batching over a frozen inference program.

The reference ships AnalysisPredictor as a one-caller-at-a-time engine;
real serving (TF-Serving's batch scheduler, Clipper's adaptive batching)
gets its throughput from coalescing concurrent requests into one device
batch. This engine is that layer for paddle_tpu:

* concurrent callers ``submit()`` requests into a bounded
  ``AdmissionQueue`` (admission.py: backpressure + deadlines);
* one worker thread pulls same-shape-signature requests, concatenates
  their rows and PADS the batch up to a bucket boundary (powers of two
  on the leading dim by default) so the predictor's jit cache holds one
  entry per bucket — small and warm — instead of one per exact batch
  size;
* padded rows are sliced off before responses resolve, so every caller
  sees output bitwise-identical to an unbatched
  ``AnalysisPredictor.run`` of its own rows;
* the handler is a ``serving.handler`` fault-injection site
  (core/faults.py): an injected fault fails that batch's requests
  individually and the loop keeps serving — never a wedged queue.

Telemetry: serving.requests / batches / batched_rows / padded_rows /
rejects / deadline_expired / handler_errors counters, serving.batch_fill
histogram, serving.request_ms + serving.batch_ms timers,
serving.queue_depth gauge — rendered by tools/perf_report.py's
"Serving" section.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core import costmodel, faults, incidents, telemetry, trace
from ..core import flags as _flags
from ..core.analysis import lockdep
from ..core.flags import flag as _flag
from .admission import (AdmissionQueue, EngineClosedError, InferenceRequest,
                        ServingError)
from .health import (DRAINING, READY, STOPPED, SWAPPING, HealthState,
                     ReadyGate)


def _pow2_buckets(max_batch: int) -> List[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class ServingConfig:
    """Engine knobs; defaults come from the FLAGS_serving_* registry."""

    def __init__(self, max_batch_size: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None):
        self.max_batch_size = int(
            _flag("serving_max_batch_size") if max_batch_size is None
            else max_batch_size)
        self.batch_timeout_ms = float(
            _flag("serving_batch_timeout_ms") if batch_timeout_ms is None
            else batch_timeout_ms)
        self.max_queue_depth = int(
            _flag("serving_max_queue_depth") if max_queue_depth is None
            else max_queue_depth)
        self.default_deadline_ms = float(
            _flag("serving_default_deadline_ms") if default_deadline_ms is None
            else default_deadline_ms)
        # strict typed parse (core/flags.py): a zero-valued or
        # non-monotonic bucket list raises BucketConfigError instead of
        # being silently reordered — the autotuner searches this surface
        # and malformed points must be loud
        if buckets is None:
            buckets = _flags.parse_buckets(_flag("serving_buckets"),
                                           "FLAGS_serving_buckets")
        else:
            buckets = _flags.parse_buckets(buckets, "buckets")
        self.buckets = buckets or _pow2_buckets(self.max_batch_size)

    def bucket(self, rows: int) -> int:
        """Smallest boundary >= rows; an oversized request is its own
        bucket (compiles once for that exact size)."""
        for b in self.buckets:
            if rows <= b:
                return b
        return rows


class ServingEngine:
    """Thread-safe micro-batching front end over an AnalysisPredictor.

    Lifecycle: ``start()`` (optionally warming every bucket) → concurrent
    ``submit``/``infer`` → ``close(drain=True)``. Only the single worker
    thread (plus warmup, which runs before it starts) touches the
    predictor, so the predictor itself needs no locking.
    """

    def __init__(self, predictor, config: Optional[ServingConfig] = None,
                 version: int = 0):
        self.predictor = predictor
        self.config = config or ServingConfig()
        self.queue = AdmissionQueue(self.config.max_queue_depth,
                                    self.config.default_deadline_ms)
        self._thread: Optional[threading.Thread] = None
        self._infer_lock = lockdep.lock("engine.infer")
        self._swap_lock = lockdep.lock("engine.swap")
        self._feed_names = list(predictor.feed_names)
        self._fetch_names = list(predictor.fetch_names)
        # liveness/readiness state machine (health.py): STARTING until
        # start() finishes warmup — a router/LB polling /healthz never
        # routes to a cold replica
        self.health = HealthState()
        self.version = int(version)
        # per-bucket cost/memory footprints captured at warmup
        # (core/costmodel.py ProgramCost records, keyed by bucket size)
        self._bucket_costs: Dict[int, Any] = {}

    # -- client surface ------------------------------------------------------
    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    def submit(self, feeds: Dict[str, Any],
               deadline_ms: Optional[float] = None) -> InferenceRequest:
        """Enqueue one request (non-blocking). feeds maps every feed name
        to an array whose dim 0 is the request's rows; all feeds must
        agree on rows. Raises ServerOverloadedError / EngineClosedError."""
        arrs = {}
        rows = None
        for n in self._feed_names:
            if n not in feeds:
                raise ValueError(f"missing input '{n}'; "
                                 f"need {self._feed_names}")
            v = np.asarray(feeds[n])
            if v.ndim == 0:
                raise ValueError(f"input '{n}' needs a leading batch dim")
            if rows is None:
                rows = v.shape[0]
            elif v.shape[0] != rows:
                raise ValueError(
                    f"inputs disagree on rows: '{n}' has {v.shape[0]}, "
                    f"expected {rows}")
            arrs[n] = v
        extra = set(feeds) - set(self._feed_names)
        if extra:
            raise ValueError(f"unknown inputs {sorted(extra)}; "
                             f"feeds are {self._feed_names}")
        # the submitter's sampled trace context (if any) rides the request
        # into the batch worker, which reconstructs the queue-wait/batch/
        # predictor span timeline against it
        return self.queue.submit(arrs, rows, deadline_ms,
                                 trace=trace.current())

    def infer(self, feeds: Dict[str, Any],
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None) -> List[np.ndarray]:
        """Blocking submit-and-wait; returns fetches in fetch_names order."""
        return self.submit(feeds, deadline_ms).result(timeout)

    def stats(self) -> Dict[str, Any]:
        """Live stats: cumulative serving.* counters (flat, as before)
        plus request/batch latency percentiles and rolling-window rates —
        the /v1/stats payload."""
        c = telemetry.counters()
        out = {k.split(".", 1)[1]: int(v) for k, v in c.items()
               if k.startswith("serving.") and isinstance(v, (int, float))}
        out["queue_depth"] = self.queue.depth()
        out["model_version"] = self.version
        out["status"] = self.health.state
        out["ready"] = self.health.is_ready()
        # the live serving config (an autotune trial flips it via
        # swap_predictor(config=...) — visible here so the trial can
        # verify the candidate actually took)
        out["serving_config"] = {
            "max_batch_size": self.config.max_batch_size,
            "batch_timeout_ms": self.config.batch_timeout_ms,
            "buckets": list(self.config.buckets)}
        hists = telemetry.snapshot()["hists"]
        for key in ("serving.request_ms", "serving.batch_ms"):
            h = hists.get(key)
            if h:
                out[key.split(".", 1)[1]] = {
                    "count": h["count"], "avg": h["avg"], "p50": h["p50"],
                    "p95": h["p95"], "p99": h["p99"], "max": h["max"]}
        win = telemetry.windowed()
        wout = {"seconds": win["window_s"]}
        wc = win["counters"].get("serving.requests")
        if wc:
            wout["request_rate"] = wc["rate"]
        wb = win["counters"].get("serving.batches")
        if wb:
            wout["batch_rate"] = wb["rate"]
        for key in ("serving.request_ms", "serving.batch_ms"):
            wh = win["hists"].get(key)
            if wh:
                short = key.split(".", 1)[1]
                wout[short] = {"count": wh["count"], "rate": wh["rate"],
                               "p50": wh["p50"], "p95": wh["p95"],
                               "p99": wh["p99"]}
        out["window"] = wout
        if self._bucket_costs:
            # per-warmed-bucket cost/memory footprints + the composed
            # HBM ledger (core/costmodel.py) — the capacity-planning
            # numbers a router/operator reads off /v1/stats
            out["memory"] = {
                "buckets": {str(b): {
                    "peak_bytes": rec.peak_bytes,
                    "temp_bytes": rec.temp_bytes,
                    "arg_bytes": rec.arg_bytes,
                    "flops": rec.flops,
                    "roofline": rec.roofline()}
                    for b, rec in sorted(self._bucket_costs.items())},
                "ledger": costmodel.ledger()}
        return out

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._thread is not None:
            return self
        if self.queue.closed:
            raise EngineClosedError("engine was closed; build a new one")
        if warmup:
            self.warmup()
        self._thread = threading.Thread(target=self._loop,
                                        name="pt-serving-engine",
                                        daemon=True)
        self._thread.start()
        self.health.set(READY)
        return self

    def warmup(self) -> int:
        """Pre-compile every bucket with zero feeds so the first real
        request never pays a compile. Returns the number of fresh
        compiles (serving.warmup_compiles)."""
        fresh, costs = self._warm(self.predictor, locked=True)
        self._publish_bucket_costs(costs)
        return fresh

    def _warm(self, predictor, locked: bool = False, config=None):
        """Run every bucket through ``predictor`` once; returns (fresh
        compile count, {bucket: ProgramCost}). ``locked`` guards runs of
        the LIVE predictor with the infer lock; a swap candidate is
        private until the flip, and warming it unlocked keeps the old
        predictor serving (zero downtime) while the new one compiles.
        ``config`` warms a swap CANDIDATE's bucket set (a config flip
        rides the same machinery as a model flip)."""
        config = config or self.config
        specs = predictor.feed_specs()
        for n, (shape, _dtype) in specs.items():
            if any(d is None or d < 0 for d in shape[1:]):
                telemetry.counter_add("serving.warmup_skipped", 1, feed=n)
                return 0, {}   # non-batch dynamic dims: nothing to build
        before = telemetry.counter_get("predictor.compiles")
        costs: Dict[int, Any] = {}
        with telemetry.timer("serving.warmup_ms"):
            for b in config.buckets:
                feed = {n: np.zeros((b,) + tuple(shape[1:]), dtype=dtype)
                        for n, (shape, dtype) in specs.items()}
                if locked:
                    with self._infer_lock:
                        # pt-lint: disable=blocking-call-under-lock(warmup of the LIVE predictor must exclude the worker's batches; the lock is exactly what serialises them)
                        predictor.run(feed)
                else:
                    predictor.run(feed)
                # per-bucket cost/memory footprint (captured by the
                # predictor when FLAGS_cost_capture is on)
                rec = getattr(predictor, "_last_cost", None)
                if rec is not None:
                    costs[b] = rec
        fresh = telemetry.counter_get("predictor.compiles") - before
        if fresh:
            telemetry.counter_add("serving.warmup_compiles", fresh)
        return int(fresh), costs

    def _publish_bucket_costs(self, costs: Dict[int, Any]):
        """Publish the warmed buckets' footprints on the HBM ledger:
        mem.serving.bucket<B>_peak_bytes gauges (full capture only — the
        peak needs memory_analysis) + the /v1/stats memory section."""
        if not costs:
            return
        self._bucket_costs = dict(costs)
        for b, rec in costs.items():
            if rec.peak_bytes:
                telemetry.gauge_set(f"mem.serving.bucket{b}_peak_bytes",
                                    int(rec.peak_bytes))
        costmodel.refresh_ledger()

    def swap_predictor(self, predictor, version: Optional[int] = None,
                       warmup: bool = True, config=None) -> int:
        """Zero-downtime model swap: warm every bucket on the NEW
        predictor while the old one keeps serving, then flip atomically
        under the infer lock (the in-flight batch completes on the old
        predictor first — every response is served entirely by one
        version, never a mix). Readiness is false (SWAPPING) for the
        duration so a router drains new traffic away from the warming
        replica. Returns the number of fresh warmup compiles; on any
        failure the old predictor stays live and readiness is restored.
        ``replica.swap`` is a fault-injection site (core/faults.py).

        ``config`` flips the ServingConfig (bucket set / batch bounds)
        together with the predictor — the autotuner's online A/B trial
        (core/tuner.py) rides this to apply a candidate serving config
        to ONE replica with the same warm-then-flip safety as a model
        swap. Admission-queue bounds (max_queue_depth, default deadline)
        are fixed at engine construction and are NOT flipped."""
        with self._swap_lock:
            faults.maybe_fail("replica.swap", version=version)
            # clients feed by NAME and read outputs by the engine's stable
            # fetch schema, so a swap needs identical feed names and fetch
            # arity; fresh auto-generated fetch VAR names (a republished
            # model) are fine — the engine keeps its original output keys
            if list(predictor.feed_names) != self._feed_names or \
                    len(predictor.fetch_names) != len(self._fetch_names):
                raise ValueError(
                    f"swap candidate signature mismatch: feeds "
                    f"{list(predictor.feed_names)} / {len(predictor.fetch_names)} "
                    f"fetches, serving {self._feed_names} / "
                    f"{len(self._fetch_names)} fetches")
            with ReadyGate(self.health, SWAPPING), \
                    telemetry.timer("serving.swap_ms"):
                # pt-lint: disable=blocking-call-under-lock(the swap lock serialises SWAPS only — warmup compiles run unlocked while the old predictor keeps serving; that is the zero-downtime design)
                fresh, costs = self._warm(predictor, locked=False,
                                          config=config) \
                    if warmup else (0, {})
                with self._infer_lock:
                    self.predictor = predictor
                    if config is not None:
                        self.config = config
                    if version is not None:
                        self.version = int(version)
                self._publish_bucket_costs(costs)
            telemetry.counter_add("serving.swaps", 1, version=self.version,
                                  warmup_compiles=fresh)
            return fresh

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop admission; with drain=True the worker finishes the backlog
        before exiting, else queued requests fail with EngineClosedError.
        Readiness drops to DRAINING immediately (the router stops routing
        here) and the state ends STOPPED."""
        self.health.set(DRAINING)
        self.queue.close(drain=drain)
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.health.set(STOPPED)

    # -- engine loop ---------------------------------------------------------
    def _signature(self, req: InferenceRequest):
        return tuple((n, req.feeds[n].shape[1:], str(req.feeds[n].dtype))
                     for n in self._feed_names)

    def _loop(self):
        while True:
            taken = self.queue.take_batch(self._signature,
                                          self.config.max_batch_size,
                                          self.config.batch_timeout_ms)
            if taken is None:
                return
            # SLO watchdog hook (core/incidents.py): armed replicas
            # evaluate the rule set on the batch cadence
            incidents.tick()
            _sig, batch = taken
            if not batch:
                continue
            try:
                self._serve_batch(batch)
            except BaseException as e:   # the loop must outlive any batch
                telemetry.counter_add("serving.handler_errors", len(batch),
                                      exc=type(e).__name__)
                for req in batch:
                    if not req.done():
                        req.fail(e if isinstance(e, ServingError)
                                 else ServingError(
                                     f"serving handler failed: {e!r}"))

    def _serve_batch(self, batch: List[InferenceRequest]):
        import time as _time

        rows = sum(r.rows for r in batch)
        bucket = self.config.bucket(rows)
        # requests whose submitter was inside a sampled trace get their
        # queue-wait/batch-assembly/predictor spans reconstructed here
        # (the contextvar does not cross into this worker thread)
        traced = [r for r in batch if r.trace is not None]
        t_dequeue = _time.time() if traced else 0.0
        t_run0 = t_run1 = 0.0
        try:
            faults.maybe_fail("serving.handler", batch_rows=rows,
                              requests=len(batch))
            feed = {}
            for n in self._feed_names:
                parts = [r.feeds[n] for r in batch]
                if bucket > rows:
                    pad_shape = (bucket - rows,) + parts[0].shape[1:]
                    parts.append(np.zeros(pad_shape, dtype=parts[0].dtype))
                feed[n] = parts[0] if len(parts) == 1 \
                    else np.concatenate(parts, axis=0)
            if traced:
                t_run0 = _time.time()
            with self._infer_lock, telemetry.timer("serving.batch_ms"):
                # predictor + version read under the lock: a concurrent
                # swap_predictor flips both atomically, so this batch is
                # served entirely by ONE model version
                version = self.version
                # pt-lint: disable=blocking-call-under-lock(the single worker thread IS the serialisation point; a swap flip is the only other holder and must exclude in-flight batches)
                outs = self.predictor.run(feed)
            if traced:
                t_run1 = _time.time()
                for req in traced:
                    trace.record("serving.queue_wait", req.trace,
                                 req.enqueue_wall, t_dequeue)
                    trace.record("serving.batch_assemble", req.trace,
                                 t_dequeue, t_run0, bucket=bucket,
                                 rows=rows, requests=len(batch))
                    trace.record("serving.predictor_run", req.trace,
                                 t_run0, t_run1, bucket=bucket)
        except Exception as e:
            # per-request error responses; the queue keeps moving
            telemetry.counter_add("serving.handler_errors", len(batch),
                                  exc=type(e).__name__)
            for req in traced:
                trace.record("serving.queue_wait", req.trace,
                             req.enqueue_wall, t_dequeue)
                trace.record("serving.batch_error", req.trace, t_dequeue,
                             _time.time(), error=type(e).__name__)
            for req in batch:
                req.fail(e)
            return
        telemetry.counter_add("serving.batches", 1)
        telemetry.counter_add("serving.batched_rows", rows)
        if bucket > rows:
            telemetry.counter_add("serving.padded_rows", bucket - rows)
        telemetry.observe("serving.batch_fill", rows / bucket)
        offset = 0
        now = _time.monotonic()
        for req in batch:
            sliced = [o[offset:offset + req.rows]
                      if getattr(o, "ndim", 0) >= 1 and len(o) == bucket
                      else o   # non-per-row fetch: hand it through whole
                      for o in outs]
            offset += req.rows
            req.served_version = version
            req.resolve(sliced)
            telemetry.observe("serving.request_ms",
                              (now - req.enqueue_t) * 1e3, kind="timer")
