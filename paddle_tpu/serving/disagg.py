"""Disaggregated prefill/decode serving — KV page shipment wire format.

DistServe-style tiering on the paged store: prefill replicas
(``FLAGS_decode_role=prefill``) burn the compute-bound prompt pass and
ship the finished KV pages; decode replicas
(``role=decode`` + ``FLAGS_disagg_prefill_urls``) install the pages
and run the memory-bound generation steps. ``role=unified`` (the
default) keeps today's behaviour — and is the FALLBACK: a decode
replica that cannot fetch or verify a shipment prefills locally
(``disagg.fallback_prefills``), so a dead prefill tier degrades
throughput, never correctness.

Wire format (version 1), reusing the checkpoint CRC discipline
(core/checkpoint.py: zlib.crc32 over the raw array bytes):

    b"PTKV" | u8 version | u32 header_len | header JSON | payload

The header carries page_size / n_pages / tokens / dtype, the payload
layout (layer name order + shapes), a CRC PER PAGE per layer, and the
CRC of the shipped first-token logits row. ``unpack_shipment``
re-CRCs every page and raises typed ``ShipmentCRCError`` on any
mismatch (``disagg.crc_rejects``) — a corrupted shipment is rejected
and re-prefilled, never served. Telemetry: disagg.ships /
ship_bytes / installs / crc_rejects / fallback_prefills.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Sequence

import numpy as np

from ..core import telemetry

MAGIC = b"PTKV"
VERSION = 1


class ShipmentError(ValueError):
    """Malformed or mismatched KV page shipment."""


class ShipmentCRCError(ShipmentError):
    """A shipped page's CRC did not verify — the shipment is corrupt."""


def _crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def pack_shipment(tokens: Sequence[int], page_size: int,
                  layer_pages: Dict[str, np.ndarray],
                  logits_row: np.ndarray) -> bytes:
    """Serialize one prompt's finished KV pages + first-token logits.

    ``layer_pages``: pool feed name -> [n_pages, page_size, kv_dim]
    host array (the prompt's pages, in page-table order)."""
    names = sorted(layer_pages)
    if not names:
        raise ShipmentError("shipment needs at least one layer")
    first = layer_pages[names[0]]
    n_pages = int(first.shape[0])
    header: Dict[str, Any] = {
        "page_size": int(page_size),
        "n_pages": n_pages,
        "kv_dim": int(first.shape[2]),
        "dtype": str(first.dtype),
        "tokens": [int(t) for t in np.asarray(tokens).reshape(-1)],
        "layers": names,
        "page_crcs": {},
        "logits_dtype": str(np.asarray(logits_row).dtype),
        "logits_len": int(np.asarray(logits_row).size),
        "logits_crc": _crc(np.asarray(logits_row)),
    }
    payload = bytearray()
    for name in names:
        arr = np.ascontiguousarray(layer_pages[name])
        if arr.shape != first.shape or arr.dtype != first.dtype:
            raise ShipmentError(
                f"layer {name} shape/dtype {arr.shape}/{arr.dtype} "
                f"disagrees with {first.shape}/{first.dtype}")
        header["page_crcs"][name] = [_crc(arr[p]) for p in range(n_pages)]
        payload += arr.tobytes()
    payload += np.ascontiguousarray(logits_row).tobytes()
    hdr = json.dumps(header, sort_keys=True).encode("utf-8")
    return (MAGIC + struct.pack("<BI", VERSION, len(hdr)) + hdr +
            bytes(payload))


def unpack_shipment(blob: bytes) -> Dict[str, Any]:
    """Parse + CRC-verify a shipment. Returns {page_size, n_pages,
    tokens, layers: {name: [n_pages, P, kv_dim] array}, logits}.
    Raises ShipmentCRCError (counted as ``disagg.crc_rejects``) on any
    per-page or logits CRC mismatch, ShipmentError on malformed
    framing — both are REJECTIONS: the caller must re-prefill."""
    if len(blob) < len(MAGIC) + 5 or blob[:len(MAGIC)] != MAGIC:
        raise ShipmentError("not a KV page shipment (bad magic)")
    ver, hdr_len = struct.unpack_from("<BI", blob, len(MAGIC))
    if ver != VERSION:
        raise ShipmentError(f"unsupported shipment version {ver} "
                            f"(this build speaks {VERSION})")
    off = len(MAGIC) + 5
    try:
        header = json.loads(blob[off:off + hdr_len].decode("utf-8"))
    except Exception as e:
        raise ShipmentError(f"unreadable shipment header: {e!r}")
    off += hdr_len
    n_pages = int(header["n_pages"])
    shape = (n_pages, int(header["page_size"]), int(header["kv_dim"]))
    dtype = np.dtype(header["dtype"])
    per_layer = int(np.prod(shape)) * dtype.itemsize
    layers: Dict[str, np.ndarray] = {}
    for name in header["layers"]:
        raw = blob[off:off + per_layer]
        if len(raw) != per_layer:
            raise ShipmentError(f"truncated shipment payload at {name}")
        arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        crcs = header["page_crcs"][name]
        for p in range(n_pages):
            if _crc(arr[p]) != int(crcs[p]):
                telemetry.counter_add("disagg.crc_rejects", 1, layer=name)
                raise ShipmentCRCError(
                    f"CRC mismatch on shipped page {p} of {name} — "
                    f"rejecting the shipment")
        layers[name] = arr
        off += per_layer
    ldtype = np.dtype(header["logits_dtype"])
    llen = int(header["logits_len"])
    raw = blob[off:off + llen * ldtype.itemsize]
    if len(raw) != llen * ldtype.itemsize:
        raise ShipmentError("truncated shipment logits")
    logits = np.frombuffer(raw, dtype=ldtype).reshape(llen)
    if _crc(logits) != int(header["logits_crc"]):
        telemetry.counter_add("disagg.crc_rejects", 1, layer="logits")
        raise ShipmentCRCError("CRC mismatch on shipped logits — "
                               "rejecting the shipment")
    return {"page_size": int(header["page_size"]), "n_pages": n_pages,
            "tokens": [int(t) for t in header["tokens"]],
            "layers": layers, "logits": logits}


def fetch_prefill(url: str, prompt: np.ndarray,
                  timeout: float = 30.0) -> bytes:
    """POST the prompt to ``/v1/prefill`` and return the raw shipment
    bytes (HTTP errors raise ShipmentError).

    ``url`` may point at a prefill replica directly OR at the cluster
    router, which forwards to a live prefill-tier member
    (``router.prefill_forwards``) — the indirection keeps prefill-tier
    membership changes (respawn after a crash, ``scale_tier``)
    invisible to decode replicas. A path component in ``url`` is
    honoured as a prefix (e.g. ``http://router:8080/v1/prefill``);
    a bare host:port URL gets ``/v1/prefill`` appended."""
    import http.client
    import urllib.parse

    u = urllib.parse.urlparse(url)
    path = u.path.rstrip("/")
    if not path.endswith("/v1/prefill"):
        path = path + "/v1/prefill"
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    try:
        body = json.dumps(
            {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)]}
        ).encode("utf-8")
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise ShipmentError(
                f"prefill tier {url} answered {resp.status}: "
                f"{data[:200]!r}")
        return data
    finally:
        conn.close()
