"""Telemetry-driven request router — the traffic side of the cluster
serving control plane.

One router fronts N ServingEngine replicas (serving/cluster.py launches
and monitors them; this module never owns a process). Three jobs:

* **balance** — a probe thread polls every replica's ``/healthz``
  (readiness) and ``/v1/stats`` (queue_depth, model_version) every
  ``FLAGS_router_health_interval_s``; a dispatch picks the READY replica
  with the lowest load score (scraped queue depth + the router's own
  in-flight count toward that replica, which covers the probe gap);
* **fail over** — a dispatch that dies (connection refused/reset, socket
  timeout, 429/500/503 from the replica) is retried on a different
  surviving replica under the request's deadline, on the shared
  core/retry.py schedule (the same backoff/deadline semantics the PS
  transport uses). The failed replica is marked down immediately so the
  next pick skips it without waiting for the probe;
* **dedup** — every request carries an id (client ``X-Request-Id`` or
  router-minted). Successful responses are cached in a bounded map for
  ``FLAGS_router_dedup_capacity`` ids, so a CLIENT retry of an
  already-answered id replays the response (``router.dedup_hits``)
  instead of re-dispatching — with the replica hop being pure inference,
  this closes the exactly-once loop end to end: one accepted request id,
  one served response, no matter how many wire attempts either hop took.

Tracing: the router opens the request's root span and forwards the
client's ``X-Request-Id`` on the replica hop, where the PR 4 HTTP server
pins its own root span to the same id — one trace id across both
processes, mergeable by tools/trace_view.py. Each attempt is a
``router.dispatch`` child span and a fault-injection site
(core/faults.py) of the same name, so chaos runs can kill dispatches in
the router itself, not just replicas under it.

Telemetry: router.requests / retries / failovers / rejects / dedup_hits
/ replica_down / swaps / replica_deaths counters, router.request_ms +
router.dispatch_ms timers — rendered by tools/perf_report.py's "Router"
section and the /metrics plane.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from ..core import faults, incidents, retry, telemetry, trace
from ..core.analysis import lockdep
from ..core.flags import flag as _flag
from .admission import ServingError


class NoReplicaAvailableError(ServingError):
    """No READY replica to dispatch to (all down/draining/swapping)."""


class ReplicaHandle:
    """The router's view of one replica: endpoint + last probed state."""

    def __init__(self, name: str, url: str, role: str = "unified"):
        self.name = name
        self.url = url.rstrip("/")
        # disaggregated-serving tier (serving/disagg.py): 'prefill'
        # replicas only take /v1/prefill shipments, 'decode' and
        # 'unified' carry /v1/generate traffic (route_generate)
        self.role = str(role or "unified").lower()
        self._lock = lockdep.lock("router.replica")
        self.ready = False
        self.alive = True
        self.status = "unknown"     # /healthz status string (health.py)
        self.queue_depth = 0
        self.inflight = 0           # router-side dispatches in progress
        self.model_version: Optional[int] = None
        self.last_probe_t = 0.0
        self.consecutive_failures = 0
        # bounded ring of (epoch_ts, ms) of successful dispatches: the
        # per-ARM latency evidence an online autotune trial compares
        # (router-side so it works for the in-process cluster backend,
        # whose replicas share one telemetry registry)
        self.dispatch_samples: "deque" = deque(maxlen=512)

    # -- state updates (probe thread + dispatch path) ------------------------
    def mark_probe(self, ready: bool, stats: Optional[Dict[str, Any]] = None):
        with self._lock:
            was_ready = self.ready
            self.ready = ready
            self.alive = True
            self.last_probe_t = time.monotonic()
            self.consecutive_failures = 0
            if stats:
                self.queue_depth = int(stats.get("queue_depth", 0))
                if stats.get("status"):
                    self.status = str(stats["status"])
                if stats.get("model_version") is not None:
                    self.model_version = int(stats["model_version"])
        if ready and not was_ready:
            telemetry.counter_add("router.replica_up", 1, replica=self.name)

    def mark_down(self, reason: str = ""):
        with self._lock:
            was_ready = self.ready
            self.ready = False
            self.status = "down"
            self.consecutive_failures += 1
        if was_ready:
            telemetry.counter_add("router.replica_down", 1,
                                  replica=self.name, reason=reason)

    def swapping(self) -> bool:
        """Not-ready because of a model swap: the replica still SERVES
        (the old version keeps running while the new one warms) — a
        legal last-resort dispatch target when nothing is READY."""
        with self._lock:
            return self.status == "swapping"

    def rebind(self, url: str):
        """Point this slot at a respawned replica (cluster.py)."""
        with self._lock:
            self.url = url.rstrip("/")
            self.ready = False
            self.queue_depth = 0
            self.inflight = 0
            self.consecutive_failures = 0

    def record_dispatch(self, ms: float):
        with self._lock:
            self.dispatch_samples.append((time.time(), float(ms)))

    def dispatch_latencies(self, since_ts: float = 0.0) -> List[float]:
        with self._lock:
            return [ms for ts, ms in self.dispatch_samples
                    if ts >= since_ts]

    # -- balancing -----------------------------------------------------------
    def probe_age_s(self) -> Optional[float]:
        """Seconds since the last SUCCESSFUL probe; None before the
        first. The staleness evidence behind score()'s failure penalty
        and the /v1/stats `last_probe_age_s` field."""
        with self._lock:
            t = self.last_probe_t
        if not t:
            return None
        return round(time.monotonic() - t, 3)

    def score(self) -> int:
        """Load estimate: last scraped queue depth + our own in-flight
        dispatches (covers requests sent since the last probe), plus a
        penalty per consecutive probe failure — a handle whose probe
        just failed keeps its STALE queue depth (mark_down never zeroes
        it), and the penalty stops that stale depth from reading as
        "least loaded" next to replicas with fresh evidence."""
        with self._lock:
            return (self.queue_depth + self.inflight
                    + self.consecutive_failures)

    def snapshot(self) -> Dict[str, Any]:
        age = self.probe_age_s()
        with self._lock:
            return {"name": self.name, "url": self.url, "role": self.role,
                    "ready": self.ready,
                    "queue_depth": self.queue_depth,
                    "inflight": self.inflight,
                    "model_version": self.model_version,
                    "consecutive_failures": self.consecutive_failures,
                    "probe_failures": self.consecutive_failures,
                    "last_probe_age_s": age,
                    "stale": self.consecutive_failures > 0}


def _http_json(method: str, url: str, path: str,
               body: Optional[bytes] = None,
               headers: Optional[Dict[str, str]] = None,
               timeout: float = 10.0) -> Tuple[int, Dict[str, Any]]:
    """One HTTP exchange with a replica; stdlib http.client (a fresh
    localhost connection per attempt — failover correctness over
    keep-alive micro-optimisation). Connection-level failures raise
    (ConnectionError/OSError/socket.timeout); HTTP status is returned."""
    host, _, port = url.rpartition("://")[2].partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            doc = json.loads(raw) if raw else {}
        except ValueError:
            doc = {"error": f"non-JSON reply ({len(raw)} bytes)"}
        return resp.status, doc
    finally:
        conn.close()


class Router:
    """Health-checked, load-balanced, retrying front end over N replica
    endpoints. Thread-safe; serve it with RouterHTTPServer."""

    #: replica HTTP statuses that mean "this attempt failed, another
    #: replica may succeed" — 429 overload, 500 handler failure, 503
    #: draining/closed. 400/404 are the client's fault and 504 means the
    #: deadline died in the replica queue (retrying cannot resurrect it).
    RETRYABLE_STATUS = (429, 500, 503)

    def __init__(self, policy: Optional[retry.RetryPolicy] = None,
                 health_interval_s: Optional[float] = None):
        self.policy = policy or retry.RetryPolicy(
            max_retries=int(_flag("router_max_retries")),
            backoff=float(_flag("router_backoff")),
            deadline=None)   # per-request deadline is applied per call
        self.health_interval_s = float(
            _flag("router_health_interval_s") if health_interval_s is None
            else health_interval_s)
        self._handles: List[ReplicaHandle] = []
        self._lock = lockdep.lock("router.core")
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # request-id dedup: id -> ("inflight", Event) | ("done", code,
        # payload). Bounded FIFO over done entries.
        self._dedup: "OrderedDict[str, tuple]" = OrderedDict()
        self._dedup_lock = lockdep.lock("router.dedup")
        self._dedup_cap = int(_flag("router_dedup_capacity"))
        self._ids = 0
        self._rr = 0   # rotating tie-break offset for equal load scores
        # online A/B traffic split (core/tuner.py OnlineTrial): when set,
        # every period-th pick steers to the trial replica and every
        # other pick EXCLUDES it, so each arm's latency evidence is pure
        self._trial: Optional[Tuple[str, float]] = None
        self._trial_count = 0
        # fleet observatory tap (core/fleetobs.FleetAggregator): when
        # attached, pick() deprioritises flagged stragglers and the
        # front end serves /fleet/status + /fleet/metrics
        self._fleet = None
        # decode-session journal (serving/session.py): replicas POST
        # per-request snapshots to /v1/session/journal; on a
        # decode-replica death route_generate re-admits the journaled
        # session on a survivor instead of losing the generation
        from .session import SessionJournal

        self.sessions = SessionJournal()

    # -- membership ----------------------------------------------------------
    def add_replica(self, name: str, url: str,
                    role: str = "unified") -> ReplicaHandle:
        handle = ReplicaHandle(name, url, role=role)
        with self._lock:
            self._handles.append(handle)
        self.probe(handle)
        return handle

    def remove_replica(self, name: str):
        with self._lock:
            self._handles = [h for h in self._handles if h.name != name]

    def handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._handles)

    # -- health probing ------------------------------------------------------
    def probe(self, handle: ReplicaHandle):
        """One readiness+stats probe; never raises."""
        try:
            code, doc = _http_json("GET", handle.url, "/healthz",
                                   timeout=max(self.health_interval_s * 4,
                                               1.0))
            handle.mark_probe(code == 200, doc)
        except (ConnectionError, OSError) as e:
            handle.mark_down(type(e).__name__)

    def _probe_loop(self):
        while not self._stop.wait(self.health_interval_s):
            for handle in self.handles():
                if self._stop.is_set():
                    return
                self.probe(handle)
            # SLO watchdog hook (core/incidents.py): failover-burst /
            # queue-saturation rules evaluate on the probe cadence
            incidents.tick()

    def start(self) -> "Router":
        if self._probe_thread is None:
            # the router is the cluster's always-on vantage point: arm
            # the SLO watchdog (failover bursts, saturation) — the probe
            # loop drives evaluation via incidents.tick()
            incidents.arm()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="pt-router-probe", daemon=True)
            self._probe_thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        incidents.disarm()

    # -- A/B traffic split (online autotune trials) --------------------------
    def set_trial(self, replica_name: str, fraction: Optional[float] = None):
        """Steer a bounded slice of traffic onto `replica_name`: every
        ~1/fraction-th routed request dispatches there, the rest stay on
        the control fleet (and skip the trial replica, keeping both
        arms' latency samples pure). Fraction clamps to (0, 0.5] — the
        control arm always carries the majority."""
        if fraction is None:
            fraction = float(_flag("tuner_traffic_fraction"))
        fraction = min(max(float(fraction), 0.01), 0.5)
        with self._lock:
            self._trial = (replica_name, fraction)
            self._trial_count = 0
        telemetry.counter_add("router.trial_split_set", 1,
                              replica=replica_name, fraction=fraction)

    def clear_trial(self):
        with self._lock:
            self._trial = None

    def trial(self) -> Optional[Tuple[str, float]]:
        with self._lock:
            return self._trial

    # -- fleet observatory ----------------------------------------------------
    def attach_fleet(self, aggregator):
        """Wire a core/fleetobs.FleetAggregator into the router: pick()
        prefers non-straggler replicas and the HTTP front end gains the
        /fleet/status + /fleet/metrics surfaces."""
        self._fleet = aggregator

    def fleet(self):
        return self._fleet

    def _straggler_names(self):
        agg = self._fleet
        if agg is None:
            return ()
        try:
            return agg.straggler_names()
        except Exception:
            return ()

    # -- balancing -----------------------------------------------------------
    def pick(self, exclude=()) -> Optional[ReplicaHandle]:
        """READY replica with the lowest load score, skipping `exclude`;
        None when nothing is routable. Equal scores round-robin (a
        rotating start offset), so an idle fleet shares work instead of
        hammering the first replica.

        With a trial traffic split active (set_trial), the steering
        schedule decides the arm first: a steered pick returns the trial
        replica (when ready), any other pick excludes it — unless the
        trial replica is the ONLY routable one, where availability beats
        arm purity."""
        handles = self.handles()
        if not handles:
            return None
        with self._lock:
            self._rr += 1
            offset = self._rr
            trial = self._trial
            steer = False
            if trial is not None:
                self._trial_count += 1
                period = max(2, int(round(1.0 / trial[1])))
                steer = (self._trial_count % period) == 0
        if trial is not None:
            trial_handle = next((h for h in handles
                                 if h.name == trial[0]), None)
            if trial_handle is not None and trial_handle not in exclude:
                if steer and trial_handle.ready:
                    telemetry.counter_quiet("router.trial_dispatches")
                    return trial_handle
                if not steer:
                    control = self._pick_from(handles, offset,
                                              set(exclude) | {trial_handle})
                    if control is not None:
                        telemetry.counter_quiet(
                            "router.trial_control_dispatches")
                        return control
                    # no control replica routable: fall through and let
                    # the trial replica carry the request
        return self._pick_from(handles, offset, exclude)

    def _pick_from(self, handles, offset, exclude) -> Optional[ReplicaHandle]:
        best = None
        best_score = None
        # fleet-flagged stragglers lose the first pass: with an attached
        # aggregator a latency outlier only carries traffic when it is
        # the last routable replica (availability beats avoidance)
        stragglers = self._straggler_names()
        for skip_stragglers in ((True, False) if stragglers else (False,)):
            for j in range(len(handles)):
                handle = handles[(offset + j) % len(handles)]
                if handle in exclude or not handle.ready:
                    continue
                if skip_stragglers and handle.name in stragglers:
                    continue
                s = handle.score()
                if best_score is None or s < best_score:
                    best, best_score = handle, s
            if best is not None:
                if stragglers and not skip_stragglers:
                    telemetry.counter_quiet("router.straggler_fallback")
                return best
        # nothing READY: fall back to a SWAPPING replica — it is alive
        # and still serving its old model version while the new one
        # warms. Without this, a kill overlapping a rolling swap leaves
        # a zero-ready window that 503s traffic the fleet could serve.
        for j in range(len(handles)):
            handle = handles[(offset + j) % len(handles)]
            if handle in exclude or not handle.swapping():
                continue
            s = handle.score()
            if best_score is None or s < best_score:
                best, best_score = handle, s
        if best is not None:
            telemetry.counter_add("router.swapping_fallback", 1,
                                  replica=best.name)
        return best

    # -- dedup cache ---------------------------------------------------------
    def _dedup_claim(self, request_id: str):
        """None -> this caller owns the id (dispatch it). Otherwise the
        cached ("done", code, payload) to replay — waiting out an
        in-flight original first, like the PS server's dedup."""
        if self._dedup_cap <= 0:
            return None
        while True:
            with self._dedup_lock:
                entry = self._dedup.get(request_id)
                if entry is None:
                    self._dedup[request_id] = ("inflight", threading.Event())
                    return None
                if entry[0] == "done":
                    return entry
                event = entry[1]
            if not event.wait(timeout=60.0):
                return None   # wedged original; dispatch rather than hang

    def _dedup_publish(self, request_id: str, code: int,
                       payload: Dict[str, Any]):
        if self._dedup_cap <= 0:
            return
        with self._dedup_lock:
            entry = self._dedup.get(request_id)
            if code == 200:
                self._dedup[request_id] = ("done", code, payload)
                while len(self._dedup) > self._dedup_cap:
                    # evict the oldest DONE entry; in-flight ones are live
                    for key in self._dedup:
                        if self._dedup[key][0] == "done":
                            del self._dedup[key]
                            break
                    else:
                        break
            else:
                # failures are not cached: the client's retry should get
                # a fresh dispatch, not a replayed error
                self._dedup.pop(request_id, None)
            if entry is not None and entry[0] == "inflight":
                entry[1].set()

    def _wait_for_replica(self, sched: retry.RetrySchedule) -> bool:
        """Block (probing) until SOME replica is routable or the
        schedule's deadline passes (5 s cap when it has none). Returns
        True when a dispatch target exists again. Does not consume retry
        attempts — an outage window is not the request's fault."""
        waited_any = False
        end = time.monotonic() + (sched.remaining(default=5.0) or 5.0)
        while time.monotonic() < end:
            for handle in self.handles():
                self.probe(handle)
            if self.pick() is not None:
                if waited_any:
                    telemetry.counter_add("router.outage_waits", 1)
                return True
            waited_any = True
            time.sleep(0.05)
        return False

    # -- the dispatch --------------------------------------------------------
    def new_request_id(self) -> str:
        with self._lock:
            self._ids += 1
            return f"rt-{id(self) & 0xFFFFFF:06x}-{self._ids}"

    def route_infer(self, inputs: Dict[str, Any],
                    deadline_ms: Optional[float] = None,
                    request_id: Optional[str] = None,
                    forward_request_id: Optional[bool] = None,
                    ) -> Tuple[int, Dict[str, Any]]:
        """Route one inference request: returns (http_code, payload).

        Retries transport failures and retryable replica statuses on the
        surviving fleet under min(deadline_ms, FLAGS_router_timeout_s);
        replays the cached response for an already-answered request id.
        Never raises — the answer is always an HTTP-shaped (code, doc)."""
        t0 = time.perf_counter()
        client_supplied = request_id is not None
        if forward_request_id is None:
            forward_request_id = client_supplied
        rid = request_id if client_supplied else self.new_request_id()
        telemetry.counter_add("router.requests", 1)

        cached = self._dedup_claim(rid)
        if cached is not None:
            telemetry.counter_add("router.dedup_hits", 1)
            payload = dict(cached[2])
            payload["deduped"] = True
            return cached[1], payload

        budget_s = float(_flag("router_timeout_s"))
        if deadline_ms is not None and deadline_ms > 0:
            budget_s = min(budget_s, deadline_ms / 1e3) \
                if budget_s > 0 else deadline_ms / 1e3
        policy = retry.RetryPolicy(
            max_retries=self.policy.max_retries,
            backoff=self.policy.backoff,
            deadline=budget_s if budget_s > 0 else None,
            max_delay=self.policy.max_delay, jitter=self.policy.jitter)
        sched = policy.start()
        per_try_cap = float(_flag("router_dispatch_timeout_s"))

        tried: set = set()
        prev_handle: Optional[ReplicaHandle] = None
        failed_over = False
        code, payload = 503, {"error": "no replica available"}
        while True:
            handle = self.pick(exclude=tried)
            if handle is None and tried:
                tried = set()               # second lap: allow re-tries
                handle = self.pick()
            if handle is None:
                # no routable replica RIGHT NOW — a kill, a swap warmup
                # or a respawn window. Wait it out under the request
                # deadline (actively re-probing) rather than shedding
                # traffic the fleet can serve in a moment.
                if self._wait_for_replica(sched):
                    continue
                telemetry.counter_add("router.rejects", 1)
                code, payload = 503, {
                    "error": "no replica available (all down, draining "
                             "or swapping)", "request_id": rid}
                break
            if prev_handle is not None and handle is not prev_handle:
                failed_over = True
                telemetry.counter_add("router.failovers", 1,
                                      frm=prev_handle.name, to=handle.name)
            prev_handle = handle
            attempt_timeout = sched.remaining(default=per_try_cap)
            if attempt_timeout is None:
                attempt_timeout = per_try_cap
            else:
                attempt_timeout = min(attempt_timeout, per_try_cap)
            body_doc = {"inputs": inputs}
            rem_ms = sched.remaining(default=None)
            if rem_ms is not None:
                body_doc["deadline_ms"] = max(rem_ms * 1e3, 1.0)
            headers = {}
            if forward_request_id:
                # the replica pins its root span to this id -> one trace
                # id across the hop (trace_view merges both logs)
                headers["X-Request-Id"] = rid
            retryable_exc: Optional[BaseException] = None
            try:
                with trace.span("router.dispatch", replica=handle.name,
                                request=rid):
                    faults.maybe_fail("router.dispatch",
                                      replica=handle.name)
                    with handle._lock:
                        handle.inflight += 1
                    try:
                        t_disp = time.perf_counter()
                        with telemetry.timer("router.dispatch_ms"):
                            code, payload = _http_json(
                                "POST", handle.url, "/v1/infer",
                                body=json.dumps(body_doc).encode(),
                                headers=headers, timeout=attempt_timeout)
                        if code == 200:
                            # per-arm latency evidence for online
                            # autotune trials (core/tuner.py)
                            handle.record_dispatch(
                                (time.perf_counter() - t_disp) * 1e3)
                    finally:
                        with handle._lock:
                            handle.inflight -= 1
            except (ConnectionError, OSError,
                    http.client.HTTPException) as e:  # incl. socket.timeout
                # a SIGKILLed replica shows up as refused/reset/timeout or
                # a torn HTTP response — all retryable on a survivor
                retryable_exc = e
                handle.mark_down(type(e).__name__)
                telemetry.counter_add("router.dispatch_errors", 1,
                                      replica=handle.name,
                                      exc=type(e).__name__)
            if retryable_exc is None:
                if code == 200:
                    payload.setdefault("request_id", rid)
                    payload["replica"] = handle.name
                    break
                if code not in self.RETRYABLE_STATUS:
                    payload.setdefault("request_id", rid)
                    break               # 400/404/504: retrying cannot help
                telemetry.counter_add("router.dispatch_errors", 1,
                                      replica=handle.name, status=code)
            tried.add(handle)
            outcome, delay = sched.note_failure()
            if outcome == retry.DEADLINE:
                telemetry.counter_add("router.deadline_exceeded", 1)
                code, payload = 504, {
                    "error": f"request exceeded its {budget_s:.3f}s "
                             f"deadline after {sched.attempt} attempts",
                    "request_id": rid}
                break
            if outcome == retry.EXHAUSTED:
                code, payload = 502, {
                    "error": f"request failed on every replica after "
                             f"{sched.attempt} attempts "
                             f"(last: {retryable_exc or code})",
                    "request_id": rid}
                break
            telemetry.counter_add("router.retries", 1)
            time.sleep(delay)
        if failed_over and code == 200:
            payload["failed_over"] = True
        self._dedup_publish(rid, code, payload)
        telemetry.observe("router.request_ms",
                          (time.perf_counter() - t0) * 1e3, kind="timer",
                          code=code)
        return code, payload

    # -- generative plane: prefix-affinity routing ---------------------------
    def pick_generate(self, prompt_ids,
                      exclude=()) -> Optional[ReplicaHandle]:
        """Prefix-AFFINITY pick for /v1/generate (serving/disagg.py
        topology): hash the prompt's full-page prefix chain
        (serving/prefix_store.prefix_chain_hash) over the ready
        decode-tier replicas, so a session's turns keep landing on the
        replica whose prefix store already holds its KV pages. Falls
        back to the unified tier when the decode tier is empty
        (``router.affinity_fallbacks``), then to the generic
        lowest-load pick. Prefill-tier replicas never carry generate
        traffic."""
        handles = [h for h in self.handles() if h not in exclude]
        decode_tier = sorted((h for h in handles
                              if h.ready and h.role == "decode"),
                             key=lambda h: h.name)
        unified_tier = sorted((h for h in handles
                               if h.ready and h.role == "unified"),
                              key=lambda h: h.name)
        tier = decode_tier or unified_tier
        if not tier:
            return self.pick(exclude=set(exclude) | {
                h for h in handles if h.role == "prefill"})
        if not decode_tier and any(h.role == "decode"
                                   for h in self.handles()):
            # a decode tier EXISTS but none of it is ready right now
            telemetry.counter_add("router.affinity_fallbacks", 1)
        from .prefix_store import ROOT_HASH, prefix_chain_hash

        tokens = [int(t) for t in prompt_ids]
        chain = prefix_chain_hash(tokens, int(_flag("decode_page_size")))
        if chain == ROOT_HASH:
            # prompt shorter than one full page: no KV pages to be
            # affine to — spread by a stable hash of the raw prompt
            # (must be process-independent: the failover re-pick and a
            # respawned router have to agree)
            key = zlib.crc32(",".join(map(str, tokens)).encode())
        else:
            key = int(chain, 16)
        handle = tier[key % len(tier)]
        telemetry.counter_quiet("router.affinity_routes")
        return handle

    def route_generate(self, prompt_ids,
                       max_new_tokens: Optional[int] = None,
                       temperature: float = 0.0,
                       seed: Optional[int] = None,
                       deadline_ms: Optional[float] = None,
                       request_id: Optional[str] = None,
                       stop_at_eos: bool = True,
                       ) -> Tuple[int, Dict[str, Any]]:
        """Route one generation to the decode plane with prefix
        affinity; retries transport failures and retryable statuses on
        the remaining tier. Never raises — always (code, payload).

        Exactly-once under client retries: an X-Request-Id already
        answered replays the cached response (same dedup cache as
        /v1/infer — a client retry during a failover can't
        double-generate). Crash survival: when a dispatch fails and the
        session journal (serving/session.py) holds accepted tokens for
        this id, the retry RESUMES the generation on a survivor —
        prompt+accepted re-prefilled, RNG state restored — and the
        journaled prefix is re-joined with the resumed tail, so the
        client sees one uninterrupted, bitwise-identical token
        stream."""
        telemetry.counter_add("router.requests", 1, plane="generate")
        client_supplied = request_id is not None
        rid = request_id if client_supplied else self.new_request_id()

        cached = self._dedup_claim(rid)
        if cached is not None:
            telemetry.counter_add("router.dedup_hits", 1,
                                  plane="generate")
            payload = dict(cached[2])
            payload["deduped"] = True
            return cached[1], payload

        budget_s = float(_flag("router_timeout_s"))
        if deadline_ms is not None and deadline_ms > 0:
            budget_s = min(budget_s, deadline_ms / 1e3) \
                if budget_s > 0 else deadline_ms / 1e3
        policy = retry.RetryPolicy(
            max_retries=self.policy.max_retries,
            backoff=self.policy.backoff,
            deadline=budget_s if budget_s > 0 else None,
            max_delay=self.policy.max_delay, jitter=self.policy.jitter)
        sched = policy.start()
        per_try_cap = float(_flag("router_dispatch_timeout_s"))
        body_doc: Dict[str, Any] = {
            "prompt_ids": [int(t) for t in prompt_ids],
            "temperature": float(temperature),
            "stop_at_eos": bool(stop_at_eos),
            "request_id": rid}
        if max_new_tokens is not None:
            body_doc["max_new_tokens"] = int(max_new_tokens)
        if seed is not None:
            body_doc["seed"] = int(seed)
        tried: set = set()
        resumed_prefix: List[int] = []
        failed_over = False
        code, payload = 503, {"error": "no replica available"}
        while True:
            # affinity stays keyed on the ORIGINAL prompt across
            # failovers — prior_tokens ride separately in the body
            handle = self.pick_generate(body_doc["prompt_ids"],
                                        exclude=tried)
            if handle is None and tried:
                tried = set()
                handle = self.pick_generate(body_doc["prompt_ids"])
            if handle is None:
                # respawn/failover window with no generate-capable
                # replica: wait it out under the deadline, re-probing —
                # the cluster controller is usually mid-respawn
                if self._wait_for_replica(sched):
                    continue
                telemetry.counter_add("router.rejects", 1)
                code, payload = 503, {
                    "error": "no generate-capable replica available",
                    "request_id": rid}
                break
            attempt_timeout = sched.remaining(default=per_try_cap)
            attempt_timeout = per_try_cap if attempt_timeout is None \
                else min(attempt_timeout, per_try_cap)
            rem = sched.remaining(default=None)
            if rem is not None:
                body_doc["deadline_ms"] = max(rem * 1e3, 1.0)
            retryable_exc: Optional[BaseException] = None
            try:
                faults.maybe_fail("router.dispatch", replica=handle.name)
                with telemetry.timer("router.dispatch_ms"):
                    code, payload = _http_json(
                        "POST", handle.url, "/v1/generate",
                        body=json.dumps(body_doc).encode(),
                        headers={"X-Request-Id": rid},
                        timeout=attempt_timeout)
            except (ConnectionError, OSError,
                    http.client.HTTPException) as e:
                retryable_exc = e
                handle.mark_down(type(e).__name__)
                telemetry.counter_add("router.dispatch_errors", 1,
                                      replica=handle.name,
                                      exc=type(e).__name__)
            if retryable_exc is None:
                if code == 200 or code not in self.RETRYABLE_STATUS:
                    payload["replica"] = handle.name
                    break
                telemetry.counter_add("router.dispatch_errors", 1,
                                      replica=handle.name, status=code)
            tried.add(handle)
            # session failover: if the dead replica journaled accepted
            # tokens for this id, the next attempt resumes instead of
            # regenerating — re-consulted every lap, so a survivor that
            # ALSO dies mid-resume hands off its own progress too
            record = self.sessions.get(rid)
            if record and record.get("accepted"):
                from .session import resume_args

                kw = resume_args(record)
                if kw["max_new_tokens"] >= 1:
                    resumed_prefix = list(kw["prior_tokens"])
                    body_doc["prior_tokens"] = kw["prior_tokens"]
                    body_doc["max_new_tokens"] = kw["max_new_tokens"]
                    if kw.get("rng_state") is not None:
                        body_doc["rng_state"] = kw["rng_state"]
                    failed_over = True
                    telemetry.counter_add("session.failovers", 1,
                                          replica=handle.name)
            outcome, delay = sched.note_failure()
            if outcome == retry.DEADLINE:
                telemetry.counter_add("router.deadline_exceeded", 1)
                code, payload = 504, {
                    "error": f"generation exceeded its {budget_s:.3f}s "
                             f"deadline after {sched.attempt} attempts",
                    "request_id": rid}
                break
            if outcome == retry.EXHAUSTED:
                code, payload = 502, {
                    "error": f"generation failed on every replica after "
                             f"{sched.attempt} attempts "
                             f"(last: {retryable_exc or code})",
                    "request_id": rid}
                break
            telemetry.counter_add("router.retries", 1)
            time.sleep(delay)
        if code == 200:
            if resumed_prefix:
                # re-join the journaled prefix with the resumed tail —
                # ONE uninterrupted stream, bitwise-identical to the
                # generation the dead replica would have produced
                payload["tokens"] = resumed_prefix + list(
                    payload.get("tokens", []))
                payload["num_tokens"] = len(payload["tokens"])
                payload["resumed"] = True
            if failed_over:
                payload["failed_over"] = True
            payload.setdefault("request_id", rid)
            self.sessions.pop(rid)
        self._dedup_publish(rid, code, payload)
        return code, payload

    def forward_prefill(self, raw_body: bytes,
                        timeout: Optional[float] = None
                        ) -> Tuple[int, bytes, str]:
        """Forward a /v1/prefill shipment pull to a ready prefill-tier
        replica (lowest load first) — the live-cluster path that lets
        decode replicas point at the ROUTER instead of pinning peer
        URLs, so prefill-tier membership changes (respawn, scale)
        never strand them. Returns (status, body_bytes, content_type);
        CRC verification stays end-to-end in the decode replica."""
        cap = float(_flag("router_dispatch_timeout_s"))
        timeout = cap if timeout is None else min(timeout, cap)
        tier = sorted((h for h in self.handles()
                       if h.ready and h.role == "prefill"),
                      key=lambda h: h.score())
        if not tier:
            return 503, json.dumps(
                {"error": "no prefill-tier replica available"}).encode(), \
                "application/json"
        last: Any = None
        for handle in tier:
            try:
                host, _, port = \
                    handle.url.rpartition("://")[2].partition(":")
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=timeout)
                try:
                    conn.request("POST", "/v1/prefill", body=raw_body,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    data = resp.read()
                    ctype = resp.getheader("Content-Type",
                                           "application/octet-stream")
                finally:
                    conn.close()
                if resp.status == 200:
                    telemetry.counter_add("router.prefill_forwards", 1,
                                          replica=handle.name)
                    return resp.status, data, ctype
                last = resp.status
                telemetry.counter_add("router.prefill_forward_errors", 1,
                                      replica=handle.name,
                                      status=resp.status)
            except (ConnectionError, OSError,
                    http.client.HTTPException) as e:
                last = e
                handle.mark_down(type(e).__name__)
                telemetry.counter_add("router.prefill_forward_errors", 1,
                                      replica=handle.name,
                                      exc=type(e).__name__)
        return 503, json.dumps(
            {"error": f"every prefill replica failed (last: {last})"}
        ).encode(), "application/json"

    # -- introspection -------------------------------------------------------
    def ready(self) -> bool:
        return any(h.ready for h in self.handles())

    def stats(self) -> Dict[str, Any]:
        c = telemetry.counters()
        out = {k.split(".", 1)[1]: int(v) for k, v in c.items()
               if k.startswith("router.") and isinstance(v, (int, float))}
        out["replicas"] = [h.snapshot() for h in self.handles()]
        out["ready"] = self.ready()
        t = self.trial()
        if t is not None:
            out["trial"] = {"replica": t[0], "fraction": t[1]}
        hists = telemetry.snapshot()["hists"]
        for key in ("router.request_ms", "router.dispatch_ms"):
            h = hists.get(key)
            if h:
                out[key.split(".", 1)[1]] = {
                    "count": h["count"], "avg": h["avg"], "p50": h["p50"],
                    "p95": h["p95"], "p99": h["p99"], "max": h["max"]}
        return out


# ---------------------------------------------------------------------------
# HTTP front end — the address clients actually talk to
# ---------------------------------------------------------------------------

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer  # noqa: E402


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _reply(self, code: int, payload: Dict[str, Any],
               headers: Optional[Dict[str, str]] = None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        router: Router = self.server.router
        if self.path == "/healthz":
            ready = router.ready()
            self._reply(200 if ready else 503,
                        {"status": "ok" if ready else "no_ready_replica",
                         "replicas": [h.snapshot()
                                      for h in router.handles()]})
        elif self.path == "/livez":
            self._reply(200, {"status": "alive"})
        elif self.path == "/v1/stats":
            self._reply(200, router.stats())
        elif self.path == "/metrics":
            body = telemetry.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/fleet/status":
            agg = router.fleet()
            if agg is None:
                self._reply(404, {"error": "no fleet aggregator attached"})
            else:
                self._reply(200, agg.status())
        elif self.path == "/fleet/metrics":
            agg = router.fleet()
            if agg is None:
                self._reply(404, {"error": "no fleet aggregator attached"})
                return
            body = agg.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        router: Router = self.server.router
        if self.path == "/v1/generate":
            try:
                length = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(length) or b"{}")
                prompt = doc["prompt_ids"]
            except (ValueError, TypeError, KeyError) as e:
                self._reply(400, {"error": f"bad generate request: {e!r}"})
                return
            # client-supplied identity: exactly-once dedup + session
            # journaling key — body request_id wins over the header
            rid = (doc.get("request_id")
                   or self.headers.get("X-Request-Id"))
            code, payload = router.route_generate(
                prompt, max_new_tokens=doc.get("max_new_tokens"),
                temperature=float(doc.get("temperature", 0.0)),
                seed=doc.get("seed"),
                deadline_ms=doc.get("deadline_ms"),
                request_id=rid,
                stop_at_eos=bool(doc.get("stop_at_eos", True)))
            self._reply(code, payload)
            return
        if self.path == "/v1/session/journal":
            # decode replicas replicate session snapshots here at
            # step-boundary cadence (serving/session.py)
            try:
                length = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(length) or b"{}")
                records = doc.get("records") or []
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad journal batch: {e!r}"})
                return
            n = router.sessions.update(records)
            self._reply(200, {"journaled": n})
            return
        if self.path == "/v1/prefill":
            # live-cluster shipment pull: decode replicas configured
            # with the ROUTER url fetch prefill shipments through here
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length)
            code, data, ctype = router.forward_prefill(raw)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path != "/v1/infer":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length) or b"{}")
            inputs = doc.get("inputs") or {}
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"bad request body: {e}"})
            return
        rid = self.headers.get("X-Request-Id")
        headers: Dict[str, str] = {}
        # the router owns the request's ROOT span; the forwarded
        # X-Request-Id pins the replica's root span to the same trace id
        with trace.root_span("router.request", trace_id=rid,
                             force=bool(rid), path=self.path) as tctx:
            code, payload = router.route_infer(
                inputs, deadline_ms=doc.get("deadline_ms"), request_id=rid)
        if tctx is not None:
            payload.setdefault("trace_id", tctx.trace_id)
            headers["X-Trace-Id"] = tctx.trace_id
        self._reply(code, payload, headers)


class RouterHTTPServer:
    """Bound router front end; start()/shutdown() own the acceptor
    thread, same lifecycle shape as ServingHTTPServer."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self._httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = router
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "RouterHTTPServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="pt-router-http", daemon=True)
            self._thread.start()
        return self

    def shutdown(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
