"""Serving — dynamic micro-batching inference behind admission control.

The subsystem the reference keeps as AnalysisPredictor-plus-your-own-
server, grown into a first-class layer (ROADMAP: "serving heavy traffic
from millions of users"):

* ``engine.ServingEngine`` — coalesces concurrent requests into padded,
  shape-bucketed batches over one frozen AnalysisPredictor; responses
  are bitwise-identical to unbatched runs;
* ``admission`` — bounded queue, typed backpressure
  (``ServerOverloadedError``), per-request deadlines, graceful drain;
* ``server`` — stdlib HTTP JSON front end + in-process ``LocalClient``,
  with every-bucket warmup.

Generative decode plane (ROADMAP item 1):

* ``decode.DecodeEngine`` — continuous-batching autoregressive
  generation with a prefill/decode phase split, slot-recycled decode
  state and per-request deadlines checked at step granularity;
  continuous-batched output is bitwise-identical to sequential decode;
* ``kv_cache.KVPagePool`` — the preallocated paged KV cache whose bytes
  book into the HBM ledger as ``mem.serving.kv_*``; a request that
  could never fit is refused with ``KVCacheExhaustedError`` at submit
  instead of OOMing mid-generation;
* int8 weight-only serving (``DecodeConfig(weight_quant="int8")``) via
  ops/quant_ops.py ``dequantize_weight``.

Cluster control plane (ROADMAP item 2):

* ``health`` — the liveness/readiness state machine behind ``/healthz``
  (503 while starting/swapping/draining) and ``/livez``;
* ``router`` — health-checked queue-depth load balancing with
  retry/failover on the shared core/retry.py schedule and request-id
  dedup (exactly-once under retries);
* ``cluster`` — ``ClusterController`` launches/supervises N replica
  processes (serving/replica.py) and rolls the fleet onto newly
  published model versions (checkpoint.publish_model COMMIT manifests)
  with zero downtime.

Load harness: tools/bench_serving.py (``--replicas N`` drives the
cluster). Chaos: ``serving.handler`` (engine loop), ``router.dispatch``
(router), ``replica.swap`` (model swap) fault sites;
tools/chaos_check.py --serving / --cluster.
"""

from .admission import (AdmissionQueue, DeadlineExceededError,
                        EngineClosedError, InferenceRequest,
                        KVCacheExhaustedError, ServerOverloadedError,
                        ServingError)
from .cluster import ClusterController, ClusterError, InprocReplica, \
    ReplicaProcess
from .decode import (DecodeConfig, DecodeEngine, GenerationRequest,
                     ShipPrefillRequest, decode_engine_from_dir,
                     demo_engine)
from .disagg import (ShipmentCRCError, ShipmentError, fetch_prefill,
                     pack_shipment, unpack_shipment)
from .engine import ServingConfig, ServingEngine
from .health import HealthState
from .kv_cache import KVPagePool
from .prefix_store import PrefixStore, prefix_chain_hash
from .router import (NoReplicaAvailableError, ReplicaHandle, Router,
                     RouterHTTPServer)
from .server import LocalClient, ServingHTTPServer, serve, serve_decode

__all__ = [
    "AdmissionQueue", "ClusterController", "ClusterError",
    "DeadlineExceededError", "DecodeConfig", "DecodeEngine",
    "EngineClosedError", "GenerationRequest", "HealthState",
    "InferenceRequest", "InprocReplica", "KVCacheExhaustedError",
    "KVPagePool", "LocalClient", "NoReplicaAvailableError",
    "PrefixStore", "ReplicaHandle", "ReplicaProcess", "Router",
    "RouterHTTPServer", "ServerOverloadedError", "ServingConfig",
    "ServingEngine", "ServingError", "ServingHTTPServer",
    "ShipPrefillRequest", "ShipmentCRCError", "ShipmentError",
    "decode_engine_from_dir", "demo_engine", "fetch_prefill",
    "pack_shipment", "prefix_chain_hash", "serve", "serve_decode",
    "unpack_shipment",
]
