"""Serving — dynamic micro-batching inference behind admission control.

The subsystem the reference keeps as AnalysisPredictor-plus-your-own-
server, grown into a first-class layer (ROADMAP: "serving heavy traffic
from millions of users"):

* ``engine.ServingEngine`` — coalesces concurrent requests into padded,
  shape-bucketed batches over one frozen AnalysisPredictor; responses
  are bitwise-identical to unbatched runs;
* ``admission`` — bounded queue, typed backpressure
  (``ServerOverloadedError``), per-request deadlines, graceful drain;
* ``server`` — stdlib HTTP JSON front end + in-process ``LocalClient``,
  with every-bucket warmup.

Load harness: tools/bench_serving.py. Chaos: the engine loop is a
``serving.handler`` fault site (tools/chaos_check.py --serving).
"""

from .admission import (AdmissionQueue, DeadlineExceededError,
                        EngineClosedError, InferenceRequest,
                        ServerOverloadedError, ServingError)
from .engine import ServingConfig, ServingEngine
from .server import LocalClient, ServingHTTPServer, serve

__all__ = [
    "AdmissionQueue", "DeadlineExceededError", "EngineClosedError",
    "InferenceRequest", "LocalClient", "ServerOverloadedError",
    "ServingConfig", "ServingEngine", "ServingError",
    "ServingHTTPServer", "serve",
]
