"""Cluster serving control plane — replicated engines behind one router,
with supervised respawn and zero-downtime rolling model swaps.

This is ROADMAP item 2: the composition of the robustness subsystems
into one deployment. The pieces and where they came from:

* N **replicas** — each a PR 4 ServingEngine process
  (serving/replica.py) with the health.py liveness/readiness machine;
* a **router** (router.py) balancing on live per-replica telemetry and
  failing over on the shared core/retry.py schedule (PR 2 heritage);
* a **model watcher** (checkpoint.ModelWatcher) polling a published-
  models root for new verified COMMIT manifests (PR 5 protocol); a new
  version triggers the **rolling swap**: one replica at a time, the
  controller POSTs /v1/admin/swap — the replica goes not-ready, warms
  every bucket on the new predictor while the OLD one keeps serving,
  flips atomically, and returns ready. At most one replica is swapping
  at any moment, so N-1 replicas carry traffic throughout: zero
  downtime, zero dropped requests, never a cold-bucket response;
* a **monitor** thread supervising replica processes: a death is
  counted (router.replica_deaths), the handle is marked down (the
  router already failed over by then), and the slot is respawned on a
  core/retry.py backoff schedule up to FLAGS_cluster_max_restarts.

Two replica backends share every code path above:

* ``inprocess=False`` (default) — real OS processes via
  ``python -m paddle_tpu.serving.replica``; what production and the
  chaos gate (tools/chaos_check.py --cluster, SIGKILL mid-load) use;
* ``inprocess=True`` — engine + HTTP server threads in THIS process;
  same wire surface on real sockets, a fraction of the startup cost —
  what most tier-1 tests use.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .. import checkpoint as _ckpt
from ..core import fleetobs, retry, telemetry
from ..core.analysis import lockdep
from ..core.flags import flag as _flag
from .router import Router, RouterHTTPServer, _http_json


class ClusterError(RuntimeError):
    """Control-plane failure (replica never came up, swap never took)."""


# ---------------------------------------------------------------------------
# replica backends
# ---------------------------------------------------------------------------

class ReplicaProcess:
    """One supervised replica OS process."""

    def __init__(self, name: str, model_root: str,
                 env: Optional[Dict[str, str]] = None,
                 serving_config=None, telemetry_log: str = "",
                 ready_timeout_s: float = 120.0, role: str = "unified",
                 decode_model_dir: Optional[str] = None,
                 prefill_urls: str = "", prefix_cache: bool = False,
                 journal_url: str = "", **_ignored):
        self.name = name
        self.model_root = model_root
        self.env = env
        self.serving_config = serving_config
        self.telemetry_log = telemetry_log
        self.ready_timeout_s = ready_timeout_s
        # disaggregated-serving tier (serving/disagg.py); forwarded to
        # the replica process and the router's affinity pick
        self.role = str(role or "unified")
        # generative replica (serving/decode.py): serve --decode-model-dir
        # over /v1/generate instead of a predictor over /v1/infer
        self.decode_model_dir = decode_model_dir
        self.prefill_urls = prefill_urls
        self.prefix_cache = bool(prefix_cache)
        self.journal_url = journal_url
        self.proc: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None
        self.version: Optional[int] = None
        self.log_tail: "deque[str]" = deque(maxlen=200)
        self._drain_thread: Optional[threading.Thread] = None

    def spawn(self):
        """Launch and block until the PT_REPLICA_READY announce line."""
        env = dict(os.environ if self.env is None else self.env)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        if self.decode_model_dir:
            cmd = [sys.executable, "-m", "paddle_tpu.serving.replica",
                   "--decode-model-dir", self.decode_model_dir,
                   "--port", "0"]
            if self.prefill_urls:
                cmd += ["--prefill-urls", self.prefill_urls]
            if self.prefix_cache:
                cmd += ["--prefix-cache"]
            if self.journal_url and self.role != "prefill":
                cmd += ["--journal-url", self.journal_url]
        else:
            cmd = [sys.executable, "-m", "paddle_tpu.serving.replica",
                   "--model-root", self.model_root, "--port", "0"]
            if self.serving_config is not None:
                cmd += ["--max-batch-size",
                        str(self.serving_config.max_batch_size),
                        "--batch-timeout-ms",
                        str(self.serving_config.batch_timeout_ms)]
        if self.telemetry_log:
            cmd += ["--telemetry-log", self.telemetry_log]
        if self.role != "unified":
            cmd += ["--role", self.role]
        self.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, bufsize=1)
        deadline = time.monotonic() + self.ready_timeout_s
        announce = None
        assert self.proc.stdout is not None
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                break
            self.log_tail.append(line.rstrip())
            if line.startswith("PT_REPLICA_READY "):
                announce = json.loads(line[len("PT_REPLICA_READY "):])
                break
            if line.startswith("PT_REPLICA_FAIL"):
                break
        if announce is None:
            rc = self.proc.poll()
            raise ClusterError(
                f"replica {self.name} never announced readiness "
                f"(exit={rc}); last output: "
                f"{list(self.log_tail)[-5:]}")
        self.url = announce["url"]
        self.version = announce.get("version")
        # keep draining stdout so the pipe never fills and wedges the child
        self._drain_thread = threading.Thread(
            target=self._drain, name=f"pt-replica-log-{self.name}",
            daemon=True)
        self._drain_thread.start()
        return self

    def _drain(self):
        try:
            assert self.proc is not None and self.proc.stdout is not None
            for line in self.proc.stdout:
                self.log_tail.append(line.rstrip())
        except (OSError, ValueError):
            pass

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self, sig: int = signal.SIGKILL):
        """Chaos/test helper: the ungraceful death."""
        if self.alive():
            assert self.proc is not None
            self.proc.send_signal(sig)

    def stop(self, timeout: float = 30.0):
        """Graceful stop: SIGTERM (replica drains), then SIGKILL."""
        if self.proc is None:
            return
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=2)


class InprocReplica:
    """Engine + HTTP server threads in this process: the same wire
    surface as ReplicaProcess at a fraction of the startup cost."""

    def __init__(self, name: str, model_root: str, serving_config=None,
                 role: str = "unified",
                 decode_model_dir: Optional[str] = None,
                 prefill_urls: str = "", prefix_cache: bool = False,
                 journal_sink=None, **_ignored):
        self.name = name
        self.model_root = model_root
        self.serving_config = serving_config
        self.role = str(role or "unified")
        self.decode_model_dir = decode_model_dir
        self.prefill_urls = prefill_urls
        self.prefix_cache = bool(prefix_cache)
        # in-process replicas journal straight into the router's
        # SessionJournal — same records, no HTTP hop
        self.journal_sink = journal_sink
        self.engine = None
        self.server = None
        self.url: Optional[str] = None
        self.version: Optional[int] = None
        self._stopped = False

    def spawn(self):
        from .server import ServingHTTPServer

        if self.decode_model_dir:
            from .decode import DecodeConfig, decode_engine_from_dir

            config = DecodeConfig(role=self.role,
                                  prefill_urls=self.prefill_urls,
                                  prefix_cache=self.prefix_cache or None)
            self.engine = decode_engine_from_dir(self.decode_model_dir,
                                                 config=config)
            if self.journal_sink is not None and self.role != "prefill":
                self.engine.journal_sink = self.journal_sink
            self.server = ServingHTTPServer(
                None, decode_engine=self.engine).start()
            self.url = self.server.url
            self.version = self.engine.version
            self.engine.start(warmup=True)
            self._stopped = False
            return self
        from ..inference import AnalysisConfig, create_predictor
        from .engine import ServingEngine

        newest = _ckpt.ModelWatcher(self.model_root).latest()
        if newest is None:
            raise ClusterError(f"no verified published model under "
                               f"{self.model_root}")
        version, model_dir = newest
        self.engine = ServingEngine(
            create_predictor(AnalysisConfig(model_dir)),
            config=self.serving_config, version=version)
        self.server = ServingHTTPServer(self.engine).start()
        self.url = self.server.url
        self.version = version
        self.engine.start(warmup=True)
        self._stopped = False
        return self

    def alive(self) -> bool:
        return not self._stopped

    def kill(self, sig: int = signal.SIGKILL):
        """Abrupt death: tear the socket down and fail the backlog —
        in-flight router dispatches see reset/refused, like a SIGKILL."""
        self._stopped = True
        if self.server is not None:
            self.server.shutdown()
        if self.engine is not None:
            self.engine.close(drain=False, timeout=5)

    def stop(self, timeout: float = 30.0):
        if self._stopped:
            return
        self._stopped = True
        if self.engine is not None:
            self.engine.close(drain=True, timeout=timeout)
        if self.server is not None:
            self.server.shutdown()


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class ClusterController:
    """Launch N replicas over a published-models root, front them with a
    router, supervise deaths, and roll the fleet onto newly published
    model versions with zero downtime.

        cluster = ClusterController(models_root, replicas=3).start()
        ... POST cluster.url + "/v1/infer" ...
        checkpoint.publish_model(models_root, new_model_dir)   # auto-rolls
        cluster.close()
    """

    def __init__(self, model_root: str, replicas: int = 2,
                 inprocess: bool = False,
                 serving_config=None,
                 replica_env: Optional[Dict[str, str]] = None,
                 router: Optional[Router] = None,
                 host: str = "127.0.0.1", router_port: int = 0,
                 model_poll_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 replica_telemetry_dir: str = "",
                 auto_swap: bool = True,
                 fleet: Optional[bool] = None,
                 roles: Optional[List[str]] = None,
                 decode_model_dir: Optional[str] = None,
                 role_counts: Optional[Dict[str, int]] = None,
                 prefix_cache: bool = False):
        self.model_root = os.path.abspath(model_root) if model_root else ""
        self.n_replicas = int(replicas)
        self.inprocess = bool(inprocess)
        self.serving_config = serving_config
        self.replica_env = replica_env
        self.model_poll_s = float(
            _flag("serving_model_poll_s") if model_poll_s is None
            else model_poll_s)
        self.max_restarts = int(
            _flag("cluster_max_restarts") if max_restarts is None
            else max_restarts)
        self.replica_telemetry_dir = replica_telemetry_dir
        self.auto_swap = bool(auto_swap)
        # disaggregated-serving topology (serving/disagg.py): roles are
        # cycled across replica slots (e.g. ["prefill", "decode"]) and
        # drive the router's role-aware prefix-affinity pick; default is
        # an all-unified fleet
        self.roles = [str(r) for r in roles] if roles else []
        # generative cluster (serving/decode.py): replicas serve
        # /v1/generate from this servable dir instead of running
        # predictors over model_root; decode-role replicas are wired to
        # journal sessions to the router and pull prefill shipments
        # through it (forward_prefill), so a respawned survivor can
        # resume any journaled session
        self.decode_model_dir = os.path.abspath(decode_model_dir) \
            if decode_model_dir else None
        self.prefix_cache = bool(prefix_cache)
        # role_counts is the TIER view of the fleet ({"prefill": 1,
        # "decode": 2}): it fixes the initial role plan AND gives
        # scale_tier() a per-role target that survives respawns. A
        # plain roles=[...] list keeps the legacy cycling behaviour.
        self.role_counts: Optional[Dict[str, int]] = \
            {str(k): int(v) for k, v in role_counts.items()} \
            if role_counts else None
        if self.role_counts is not None:
            plan: List[str] = []
            for r in sorted(self.role_counts):
                plan.extend([r] * self.role_counts[r])
            self.roles = plan
            self.n_replicas = len(plan)
        # slot → role registry: a respawn keeps the role its slot was
        # provisioned with even after tier scaling reshapes the modulo
        # cycling that assigned it
        self._slot_roles: Dict[int, str] = {}
        self.router = router or Router()
        self.router_server = RouterHTTPServer(self.router, host=host,
                                              port=router_port)
        self.replicas: List[Any] = []
        self._handles: Dict[str, Any] = {}
        self._restarts: Dict[str, int] = {}
        # monotonic name source: a slot retired by scale_to is never
        # renamed onto a later replica (router/fleet slots key by name)
        self._next_index = 0
        self._retired: set = set()
        self._scaler = None
        self._watcher: Optional[_ckpt.ModelWatcher] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # serialises rolling swaps (and guards current_version): held
        # across a whole fleet roll on purpose — swaps must not overlap
        self._swap_lock = lockdep.lock("cluster.swap")
        self._counted_dead: set = set()
        self.current_version: Optional[int] = None
        # fleet observatory (core/fleetobs.py): opt-in per cluster or
        # fleet-wide via FLAGS_fleet_enable — scrapes every member's
        # /metrics into merged fleet windows + /fleet/* on the router
        self.fleet_enabled = bool(_flag("fleet_enable")) if fleet is None \
            else bool(fleet)
        self.fleet_aggregator: Optional[fleetobs.FleetAggregator] = None

    # -- lifecycle -----------------------------------------------------------
    @property
    def url(self) -> str:
        return self.router_server.url

    def _make_replica(self, index: int, role: Optional[str] = None):
        name = f"replica-{index}"
        log = ""
        if self.replica_telemetry_dir:
            log = os.path.join(self.replica_telemetry_dir,
                               f"{name}.jsonl")
        cls = InprocReplica if self.inprocess else ReplicaProcess
        if role is None:
            role = self._slot_roles.get(index)
        if role is None:
            role = self.roles[index % len(self.roles)] if self.roles \
                else "unified"
        self._slot_roles[index] = role
        extra: Dict[str, Any] = {}
        if self.decode_model_dir:
            extra["decode_model_dir"] = self.decode_model_dir
            extra["prefix_cache"] = self.prefix_cache
            if role == "decode":
                # pull shipments THROUGH the router (forward_prefill):
                # the replica never needs to track prefill-tier
                # membership — respawns and tier scaling stay invisible
                extra["prefill_urls"] = self.url
            if role != "prefill":
                # RouterHTTPServer binds its port at construction, so
                # the journal endpoint is known before any spawn
                extra["journal_url"] = self.url + "/v1/session/journal"
                extra["journal_sink"] = self.router.sessions.update
        return cls(name, self.model_root, env=self.replica_env,
                   serving_config=self.serving_config,
                   telemetry_log=log, role=role, **extra)

    def start(self, ready_timeout_s: float = 120.0) -> "ClusterController":
        if self.decode_model_dir:
            # generative fleet: the servable dir IS the model — no
            # published-versions root, no rolling-swap watcher
            self.auto_swap = False
        else:
            self._watcher = _ckpt.ModelWatcher(self.model_root)
            newest = self._watcher.poll()
            if newest is None:
                raise ClusterError(f"no verified published model under "
                                   f"{self.model_root} — publish_model() "
                                   f"one before starting the cluster")
            # current_version is owned by the swap lock: the monitor/
            # watch threads (spawned below) read and roll it under the
            # same lock
            with self._swap_lock:
                self.current_version = newest[0]
        for _ in range(self.n_replicas):
            replica = self._make_replica(self._next_index)
            self._next_index += 1
            replica.spawn()
            self.replicas.append(replica)
            self._restarts[replica.name] = 0
            self._handles[replica.name] = self.router.add_replica(
                replica.name, replica.url,
                role=getattr(replica, "role", "unified"))
        self.router.start()
        self.router_server.start()
        self._wait_ready(ready_timeout_s)
        if self.fleet_enabled:
            self.fleet_aggregator = fleetobs.FleetAggregator()
            self.fleet_aggregator.register("router", self.url,
                                           kind="router")
            for replica in self.replicas:
                self.fleet_aggregator.register(replica.name, replica.url)
            self.router.attach_fleet(self.fleet_aggregator)
            self.fleet_aggregator.start()
        mon = threading.Thread(target=self._monitor_loop,
                               name="pt-cluster-monitor", daemon=True)
        mon.start()
        self._threads.append(mon)
        if self.auto_swap:
            watch = threading.Thread(target=self._watch_loop,
                                     name="pt-cluster-modelwatch",
                                     daemon=True)
            watch.start()
            self._threads.append(watch)
        return self

    def _wait_ready(self, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for handle in self.router.handles():
                self.router.probe(handle)
            if all(h.ready for h in self.router.handles()):
                return
            time.sleep(0.1)
        not_ready = [h.name for h in self.router.handles() if not h.ready]
        raise ClusterError(f"replicas never became ready: {not_ready}")

    def close(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        if self.fleet_aggregator is not None:
            self.fleet_aggregator.stop()
        self.router_server.shutdown()
        self.router.close()
        for replica in self.replicas:
            replica.stop()

    # -- supervision ---------------------------------------------------------
    def _monitor_loop(self):
        while not self._stop.wait(0.25):
            for replica in list(self.replicas):
                if self._stop.is_set():
                    return
                if id(replica) in self._retired:
                    continue   # scale_to drained it on purpose
                if replica.alive():
                    self._counted_dead.discard(id(replica))
                    continue
                handle = self._handles.get(replica.name)
                if handle is not None:
                    handle.mark_down("process_died")
                if id(replica) not in self._counted_dead:
                    self._counted_dead.add(id(replica))
                    telemetry.counter_add("router.replica_deaths", 1,
                                          replica=replica.name)
                    # exactly ONE incident record per death, exempt from
                    # the rate-limit window like oom/stall — two replicas
                    # dying back-to-back must both land in the ledger
                    from ..core import incidents as _incidents

                    rc = getattr(getattr(replica, "proc", None),
                                 "returncode", None)
                    _incidents.report_incident(
                        "cluster", "replica_death", 1.0,
                        context={"replica": replica.name,
                                 "role": getattr(replica, "role",
                                                 "unified"),
                                 "exit_code": rc,
                                 "signal": -rc if isinstance(rc, int)
                                 and rc < 0 else None},
                        rate_limit=False)
                if self.inprocess:
                    continue   # tests kill in-proc replicas on purpose
                if self._restarts[replica.name] >= self.max_restarts:
                    telemetry.counter_add("router.replica_abandoned", 1,
                                          replica=replica.name)
                    continue
                self._restarts[replica.name] += 1
                telemetry.counter_add("router.replica_restarts", 1,
                                      replica=replica.name)
                sched = retry.RetryPolicy(
                    max_retries=3, backoff=0.2, deadline=60.0).start()
                while not self._stop.is_set():
                    try:
                        fresh = self._make_replica(
                            int(replica.name.rsplit("-", 1)[-1]))
                        fresh.spawn()
                    except ClusterError:
                        outcome, delay = sched.note_failure()
                        if outcome != retry.RETRY:
                            telemetry.counter_add(
                                "router.replica_abandoned", 1,
                                replica=replica.name)
                            break
                        time.sleep(delay)
                        continue
                    # locate by identity: a concurrent scale_to may have
                    # shifted list positions (or retired this slot)
                    slot = next((j for j, r in enumerate(self.replicas)
                                 if r is replica), None)
                    if slot is None:
                        fresh.stop()
                        break
                    self.replicas[slot] = fresh
                    if handle is not None:
                        handle.rebind(fresh.url)
                        self.router.probe(handle)
                    role = getattr(fresh, "role", "unified")
                    if role in ("decode", "prefill"):
                        # tier membership changed: the router's prefix-
                        # affinity hash now maps some sessions elsewhere
                        telemetry.counter_add("router.affinity_remaps",
                                              1, role=role,
                                              reason="respawn")
                    if self.fleet_aggregator is not None:
                        # a respawn keeps its fleet slot — re-point the
                        # scrape at the fresh endpoint
                        self.fleet_aggregator.register(replica.name,
                                                       fresh.url)
                    # a respawn comes up on the NEWEST published version;
                    # converge it if the fleet is ahead/behind
                    if self.current_version is not None and \
                            fresh.version != self.current_version:
                        newest = _ckpt.ModelWatcher(
                            self.model_root).latest()
                        if newest is not None and \
                                newest[0] == self.current_version:
                            self._swap_one(fresh, newest[0], newest[1])
                    break

    # -- rolling model swap --------------------------------------------------
    def _watch_loop(self):
        while not self._stop.wait(self.model_poll_s):
            assert self._watcher is not None
            newest = self._watcher.poll()
            if newest is not None:
                version, path = newest
                try:
                    self.roll_to(version, path)
                except ClusterError as e:
                    telemetry.counter_add("router.swap_errors", 1,
                                          version=version,
                                          reason=type(e).__name__)
                    print(f"[cluster] rolling swap to v{version} "
                          f"failed: {e}", file=sys.stderr)

    def _swap_one(self, replica, version: int, path: str) -> bool:
        """Swap ONE replica (POST /v1/admin/swap), with retries. Returns
        success; the replica keeps serving its old version on failure."""
        sched = retry.RetryPolicy(max_retries=2, backoff=0.1,
                                  deadline=120.0).start()
        while True:
            try:
                code, doc = _http_json(
                    "POST", replica.url, "/v1/admin/swap",
                    body=json.dumps({"model_dir": path,
                                     "version": version}).encode(),
                    timeout=sched.remaining(default=90.0) or 90.0)
            except (ConnectionError, OSError) as e:
                code, doc = -1, {"error": repr(e)}
            if code == 200:
                telemetry.counter_add("router.swaps", 1,
                                      replica=replica.name,
                                      version=version)
                replica.version = version
                return True
            telemetry.counter_add("router.swap_errors", 1,
                                  replica=replica.name, version=version,
                                  status=code)
            outcome, delay = sched.note_failure()
            if outcome != retry.RETRY:
                return False
            time.sleep(delay)

    def _await_peer_ready(self, name: str, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            for handle in self.router.handles():
                if handle.name != name:
                    self.router.probe(handle)
            if any(h.ready for h in self.router.handles()
                   if h.name != name):
                return
            time.sleep(0.1)

    def roll_to(self, version: int, path: str):
        """Rolling zero-downtime swap: one replica at a time — readiness
        drops while it warms/flips, the router routes around it, and the
        next replica only starts once this one is ready again."""
        with self._swap_lock:
            failed = []
            for replica in list(self.replicas):
                if not replica.alive():
                    continue
                # never take the LAST ready replica offline: if a death/
                # respawn window has degraded the fleet, wait for a peer
                # to be ready before making this one not-ready. (If no
                # peer recovers, proceed anyway — the router's swapping-
                # fallback still dispatches to a warming replica, which
                # serves its OLD version until the flip.)
                # pt-lint: disable=blocking-call-under-lock(the swap lock exists to serialise whole fleet rolls; waiting for a ready peer under it is the zero-downtime invariant, and only swap paths contend)
                self._await_peer_ready(replica.name, timeout_s=30.0)
                # pt-lint: disable=blocking-call-under-lock(one replica swap at a time IS the rolling-swap contract; nothing but another roll waits on this lock)
                if not self._swap_one(replica, version, path):
                    failed.append(replica.name)
                    continue
                # wait for readiness to return before touching the next
                # replica: N-1 ready replicas at all times
                handle = self._handles.get(replica.name)
                deadline = time.monotonic() + 60.0
                while handle is not None and time.monotonic() < deadline:
                    self.router.probe(handle)
                    if handle.ready:
                        break
                    time.sleep(0.05)  # pt-lint: disable=blocking-call-under-lock(readiness poll between per-replica swaps, still inside the serialised fleet roll; bounded by the 60 s deadline)
            self.current_version = version
            if failed:
                raise ClusterError(
                    f"rolling swap to v{version}: replicas {failed} "
                    f"failed to swap (still serving their old version)")

    # -- autotune trial support ---------------------------------------------
    def replica_named(self, name: str):
        return next((r for r in self.replicas if r.name == name), None)

    def current_model_path(self) -> Optional[str]:
        """Path of the fleet's CURRENT model version (None when it was
        unpublished behind our back)."""
        with self._swap_lock:
            version = self.current_version
        for v, path in _ckpt.list_model_versions(self.model_root):
            if v == version:
                return path
        return None

    def retune_replica(self, name: str, timeout: float = 120.0) -> bool:
        """Re-swap ONE replica onto the fleet's CURRENT model version
        with a ServingConfig rebuilt from the live flag surface
        (POST /v1/admin/swap {reload_config: true}) — the online
        autotuner's candidate-application lever (core/tuner.py): a
        serving-config flip rides the exact zero-downtime warm-then-flip
        machinery a model swap does, on one replica only. Returns
        success; on failure the replica keeps its old config."""
        replica = self.replica_named(name)
        if replica is None or not replica.alive():
            return False
        path = self.current_model_path()
        if path is None:
            return False
        with self._swap_lock:
            version = self.current_version
        try:
            code, doc = _http_json(
                "POST", replica.url, "/v1/admin/swap",
                body=json.dumps({"model_dir": path, "version": version,
                                 "reload_config": True}).encode(),
                timeout=timeout)
        except (ConnectionError, OSError) as e:
            code, doc = -1, {"error": repr(e)}
        ok = code == 200
        telemetry.counter_add("router.swaps" if ok else "router.swap_errors",
                              1, replica=name, version=version,
                              reason="retune")
        if ok:
            # wait for readiness to return so the caller's next dispatch
            # can already land on the retuned replica
            handle = self._handles.get(name)
            deadline = time.monotonic() + timeout
            while handle is not None and time.monotonic() < deadline:
                self.router.probe(handle)
                if handle.ready:
                    break
                time.sleep(0.05)
        return ok

    # -- elastic replica scaling --------------------------------------------
    def scale_to(self, n: int, reason: str = "manual",
                 ready_timeout_s: float = 60.0) -> int:
        """Grow or shrink the replica fleet to exactly ``n``, with zero
        dropped in-flight requests.

        Grow: spawn fresh replicas (on the newest published model),
        router-register them, and wait for readiness. Shrink: pick the
        most recently added replicas, wait for a READY peer (never take
        the last ready replica offline), remove each from the router so
        no NEW dispatch lands on it, then stop it gracefully — the
        engine drains its queue before the socket closes. Each call is
        ONE scale transition: exactly one incidents.report_scale_event.
        Returns the new replica count."""
        from ..core import incidents as _incidents

        n = int(n)
        if n < 1:
            raise ClusterError("scale_to: need at least 1 replica")
        with self._swap_lock:
            old = len(self.replicas)
            if n == old:
                return old
            if n > old:
                for _ in range(n - old):
                    replica = self._make_replica(self._next_index)
                    self._next_index += 1
                    replica.spawn()
                    self.replicas.append(replica)
                    self._restarts[replica.name] = 0
                    self._handles[replica.name] = self.router.add_replica(
                        replica.name, replica.url,
                        role=getattr(replica, "role", "unified"))
                    if self.fleet_aggregator is not None:
                        self.fleet_aggregator.register(replica.name,
                                                       replica.url)
                    # converge the newcomer onto the fleet's version if
                    # a roll moved it past the newest-published default
                    if self.current_version is not None and \
                            replica.version != self.current_version:
                        newest = _ckpt.ModelWatcher(
                            self.model_root).latest()
                        if newest is not None and \
                                newest[0] == self.current_version:
                            self._swap_one(replica, newest[0], newest[1])  # pt-lint: disable=blocking-call-under-lock(scale transitions serialise with rolls on purpose; bounded by the swap timeout)
                deadline = time.monotonic() + ready_timeout_s
                while time.monotonic() < deadline:
                    for handle in self.router.handles():
                        if not handle.ready:
                            self.router.probe(handle)
                    if all(h.ready for h in self.router.handles()):
                        break
                    time.sleep(0.05)  # pt-lint: disable=blocking-call-under-lock(scale transitions serialise with rolls on purpose; bounded by ready_timeout_s)
            else:
                for _ in range(old - n):
                    victim = self.replicas[-1]
                    # pt-lint: disable=blocking-call-under-lock(the zero-downtime invariant: a peer must be ready before this replica leaves the fleet)
                    self._await_peer_ready(victim.name, timeout_s=30.0)
                    self._retired.add(id(victim))
                    self.replicas.remove(victim)
                    self._handles.pop(victim.name, None)
                    # router first: no NEW dispatch can land while the
                    # engine drains its in-flight queue below
                    self.router.remove_replica(victim.name)
                    victim.stop()
                    if self.fleet_aggregator is not None:
                        self.fleet_aggregator.deregister(victim.name)
            self.n_replicas = len(self.replicas)
        telemetry.counter_add(
            "router.scale_events", 1,
            direction="up" if n > old else "down", replicas=n)
        _incidents.report_scale_event(
            "cluster", "resize", old, n, reason=reason)
        return n

    def tier_members(self, role: str) -> List[Any]:
        """Live replicas provisioned into ``role`` (slot registry order)."""
        return [r for r in self.replicas
                if getattr(r, "role", "unified") == str(role)]

    def scale_tier(self, role: str, n: int, reason: str = "manual",
                   ready_timeout_s: float = 60.0) -> int:
        """Grow or shrink ONE role tier (prefill / decode / unified) to
        exactly ``n`` replicas, leaving the other tiers untouched — the
        serving-side analogue of a per-tier resize. New slots are
        provisioned with the requested role and keep it across respawns
        (the slot registry), so a prefill tier is supervised exactly
        like decode replicas. Returns the tier's new size."""
        from ..core import incidents as _incidents

        role = str(role)
        n = int(n)
        if n < 0:
            raise ClusterError("scale_tier: need n >= 0")
        with self._swap_lock:
            members = self.tier_members(role)
            old = len(members)
            if n == old:
                return old
            if n > old:
                for _ in range(n - old):
                    replica = self._make_replica(self._next_index,
                                                 role=role)
                    self._next_index += 1
                    replica.spawn()
                    self.replicas.append(replica)
                    self._restarts[replica.name] = 0
                    self._handles[replica.name] = self.router.add_replica(
                        replica.name, replica.url, role=role)
                    if self.fleet_aggregator is not None:
                        self.fleet_aggregator.register(replica.name,
                                                       replica.url)
                deadline = time.monotonic() + ready_timeout_s
                while time.monotonic() < deadline:
                    for handle in self.router.handles():
                        if not handle.ready:
                            self.router.probe(handle)
                    if all(h.ready for h in self.router.handles()):
                        break
                    time.sleep(0.05)  # pt-lint: disable=blocking-call-under-lock(tier transitions serialise with rolls on purpose; bounded by ready_timeout_s)
            else:
                for _ in range(old - n):
                    victim = self.tier_members(role)[-1]
                    # pt-lint: disable=blocking-call-under-lock(the zero-downtime invariant: a peer must be ready before this replica leaves the fleet)
                    self._await_peer_ready(victim.name, timeout_s=30.0)
                    self._retired.add(id(victim))
                    self.replicas.remove(victim)
                    self._handles.pop(victim.name, None)
                    self.router.remove_replica(victim.name)
                    victim.stop()
                    if self.fleet_aggregator is not None:
                        self.fleet_aggregator.deregister(victim.name)
            self.n_replicas = len(self.replicas)
            if self.role_counts is not None:
                self.role_counts[role] = n
        telemetry.counter_add(
            "router.scale_events", 1,
            direction="up" if n > old else "down", tier=role, replicas=n)
        if role in ("decode", "prefill"):
            telemetry.counter_add("router.affinity_remaps", 1, role=role,
                                  reason="scale_tier")
        _incidents.report_scale_event(
            "cluster", f"resize_{role}", old, n, reason=reason)
        return n

    def attach_scaler(self, policy) -> "ClusterController":
        """Drive replica count from a distributed.scaler.ScalerPolicy —
        the SAME policy engine the training-side ElasticRunner uses,
        pointed at serving signals (router load / queue saturation via
        the fleet observatory)."""
        self._scaler = policy
        return self

    def autoscale_tick(self, now: Optional[float] = None):
        """One policy evaluation + (maybe) one scale transition.
        Deterministic entry point — tests and external control loops
        call this instead of racing a background thread. Returns the
        executed ScaleDecision or None."""
        if self._scaler is None:
            return None
        decision = self._scaler.decide(len(self.replicas), now=now,
                                       fleet=self.fleet_aggregator)
        if decision is None:
            return None
        self.scale_to(decision.target, reason=decision.reason)
        return decision

    def start_autoscaler(self, interval_s: float = 5.0):
        """Background autoscale loop (production path; tests prefer
        autoscale_tick)."""
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.autoscale_tick()
                except ClusterError:
                    telemetry.counter_add("router.scale_errors", 1)
        t = threading.Thread(target=loop, name="pt-cluster-autoscale",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    # -- introspection -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        out = self.router.stats()
        out["current_version"] = self.current_version
        out["restarts"] = dict(self._restarts)
        out["replica_backend"] = "inprocess" if self.inprocess \
            else "process"
        if self.fleet_aggregator is not None:
            out["fleet"] = {
                "members": self.fleet_aggregator.members(),
                "stragglers": self.fleet_aggregator.straggler_names()}
        return out
