"""Admission control for the serving engine — bounded queue, deadlines,
graceful drain.

Production batching systems (TF-Serving's BatchScheduler, Clipper's
request frontend) put a policy layer between the socket and the model:
when the queue is full the right answer is a fast typed rejection the
client can retry against a replica — not an unbounded stall that turns
overload into latency collapse. This module is that layer:

* ``AdmissionQueue.submit`` rejects with ``ServerOverloadedError`` once
  ``max_depth`` requests are waiting (``serving.rejects`` counts them);
* every request may carry a deadline — a request still queued past it is
  failed with ``DeadlineExceededError`` at dequeue time instead of
  wasting batch slots on an answer nobody is waiting for;
* ``close(drain=True)`` stops admission and lets the engine loop finish
  the backlog; ``drain=False`` fails the backlog with
  ``EngineClosedError`` immediately.

The queue is signature-aware on the *take* side: ``take_batch`` gathers
FIFO-ordered requests that share the head request's shape signature so
the engine can coalesce them into one padded device batch, holding the
batch open up to ``timeout_ms`` past the head's enqueue for more rows.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import telemetry
from ..core.analysis import lockdep


class ServingError(RuntimeError):
    """Base of the serving engine's typed request failures."""


class ServerOverloadedError(ServingError):
    """Queue depth hit FLAGS_serving_max_queue_depth — retry later."""


class DeadlineExceededError(ServingError):
    """The request's deadline elapsed before it reached the model."""


class EngineClosedError(ServingError):
    """The engine is shut down (or draining) and takes no new work."""


class InferenceRequest:
    """One queued request: feeds + a future the caller blocks on.

    ``trace``/``enqueue_wall`` carry the submitter's sampled trace
    context (core/trace.py) across the thread boundary into the engine's
    batch worker, which emits the queue-wait/batch/predictor spans
    against it retroactively."""

    __slots__ = ("feeds", "rows", "deadline", "enqueue_t", "trace",
                 "enqueue_wall", "served_version", "_event", "_result",
                 "_error")

    def __init__(self, feeds: Dict[str, Any], rows: int,
                 deadline: Optional[float], trace: Optional[Any] = None):
        self.feeds = feeds
        self.rows = rows
        self.deadline = deadline          # absolute time.monotonic() or None
        self.enqueue_t = time.monotonic()
        self.trace = trace                # SpanContext of the submitter
        self.enqueue_wall = time.time() if trace is not None else 0.0
        self.served_version: Optional[int] = None  # engine.version at serve
        self._event = threading.Event()
        self._result: Optional[List[Any]] = None
        self._error: Optional[BaseException] = None

    # -- producer side (engine loop) -----------------------------------------
    def resolve(self, result: List[Any]):
        self._result = result
        self._event.set()

    def fail(self, error: BaseException):
        self._error = error
        self._event.set()

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)

    # -- consumer side (client) ----------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[Any]:
        """Block for the response; raises the typed failure on error."""
        if not self._event.wait(timeout):
            raise TimeoutError("inference request still pending after "
                               f"{timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class KVCacheExhaustedError(ServingError):
    """The request's worst-case KV-cache page need can never be satisfied
    by the preallocated pool (serving/kv_cache.py) — a typed refusal at
    admission instead of a device OOM mid-generation."""


class AdmissionQueue:
    """Bounded FIFO with deadline enforcement and drain semantics.

    ``metric_prefix`` names the counter family ("serving" for the
    micro-batching engine, "decode" for the generative decode engine) so
    both engines share one admission policy layer with separable
    telemetry."""

    def __init__(self, max_depth: int,
                 default_deadline_ms: float = 0.0,
                 metric_prefix: str = "serving"):
        self.max_depth = int(max_depth)
        self.default_deadline_ms = float(default_deadline_ms)
        self.metric_prefix = metric_prefix
        self._items: List[InferenceRequest] = []
        self._cond = lockdep.condition(f"{metric_prefix}.admission")
        self._closed = False

    def deadline_for(self, deadline_ms: Optional[float]) -> Optional[float]:
        """Resolve a caller deadline (ms from now, None = default flag)
        into an absolute time.monotonic() instant, or None."""
        ms = self.default_deadline_ms if deadline_ms is None \
            else float(deadline_ms)
        return time.monotonic() + ms / 1e3 if ms > 0 else None

    # -- admission -----------------------------------------------------------
    def submit(self, feeds: Dict[str, Any], rows: int,
               deadline_ms: Optional[float] = None,
               trace: Optional[Any] = None) -> InferenceRequest:
        return self.submit_request(InferenceRequest(
            feeds, rows, self.deadline_for(deadline_ms), trace=trace))

    def submit_request(self, req: InferenceRequest) -> InferenceRequest:
        """Admit a pre-built request (the decode engine subclasses
        InferenceRequest with generation state): bounded-depth check,
        typed backpressure, the same counters as submit()."""
        with self._cond:
            if self._closed:
                raise EngineClosedError(
                    "serving engine is shut down — no new requests")
            if len(self._items) >= self.max_depth:
                telemetry.counter_add(f"{self.metric_prefix}.rejects", 1)
                raise ServerOverloadedError(
                    f"serving queue full ({self.max_depth} requests "
                    f"waiting) — retry later")
            self._items.append(req)
            depth = len(self._items)
            self._cond.notify_all()
        telemetry.counter_add(f"{self.metric_prefix}.requests", 1)
        telemetry.gauge_set(f"{self.metric_prefix}.queue_depth", depth)
        return req

    # -- decode-engine take side ---------------------------------------------
    def poll(self, max_n: int) -> List[InferenceRequest]:
        """Non-blocking FIFO take of up to ``max_n`` requests. Expired
        requests are failed here (deadline-at-dequeue, like take_batch)
        wherever they sit, so a stale request never claims a slot."""
        out: List[InferenceRequest] = []
        with self._cond:
            now = time.monotonic()
            for req in [r for r in self._items if r.expired(now)]:
                self._items.remove(req)
                telemetry.counter_add(
                    f"{self.metric_prefix}.deadline_expired", 1)
                req.fail(DeadlineExceededError(
                    "request deadline elapsed after "
                    f"{(now - req.enqueue_t) * 1e3:.1f} ms in queue"))
            while self._items and len(out) < max_n:
                out.append(self._items.pop(0))
            depth = len(self._items)
        telemetry.gauge_set(f"{self.metric_prefix}.queue_depth", depth)
        return out

    def requeue(self, reqs: List[InferenceRequest]):
        """Put polled-but-unadmitted requests back at the FIFO head (the
        decode engine polls, checks pool headroom, and returns what it
        cannot seat yet — admission order is preserved)."""
        if not reqs:
            return
        with self._cond:
            self._items[0:0] = list(reqs)
            self._cond.notify_all()

    def wait_for_work(self, timeout_s: Optional[float]) -> bool:
        """Block until the queue holds work or is closed (or timeout);
        returns True when items are waiting."""
        with self._cond:
            if not self._items and not self._closed:
                self._cond.wait(timeout_s)
            return bool(self._items)

    # -- batch assembly ------------------------------------------------------
    def take_batch(self, signature: Callable[[InferenceRequest], Any],
                   max_rows: int, timeout_ms: float,
                   ) -> Optional[Tuple[Any, List[InferenceRequest]]]:
        """Gather one same-signature batch (FIFO head keys it), waiting up
        to ``timeout_ms`` past the head's enqueue for the batch to fill.
        Returns None only when closed AND drained (loop exit)."""
        batch: List[InferenceRequest] = []
        rows = 0
        sig = None
        flush_t = None
        with self._cond:
            while True:
                now = time.monotonic()
                # drop expired requests wherever they sit in the queue
                for req in [r for r in self._items if r.expired(now)]:
                    self._items.remove(req)
                    telemetry.counter_add(
                        f"{self.metric_prefix}.deadline_expired", 1)
                    req.fail(DeadlineExceededError(
                        "request deadline elapsed after "
                        f"{(now - req.enqueue_t) * 1e3:.1f} ms in queue"))
                # adopt the head's signature the moment work exists
                if sig is None and self._items:
                    head = self._items[0]
                    sig = signature(head)
                    flush_t = head.enqueue_t + max(0.0, timeout_ms) / 1e3
                if sig is not None:
                    for req in list(self._items):
                        if rows >= max_rows:
                            break
                        if signature(req) != sig:
                            continue
                        if batch and rows + req.rows > max_rows:
                            continue   # keep it for the next batch
                        self._items.remove(req)
                        batch.append(req)
                        rows += req.rows
                    if rows >= max_rows or now >= flush_t:
                        break
                if self._closed:
                    if batch:
                        break
                    if not self._items:
                        return None
                    continue   # closed but other-signature work remains
                wait_s = None if sig is None else max(0.0, flush_t - now)
                self._cond.wait(wait_s)
            depth = len(self._items)
            self._cond.notify_all()
        telemetry.gauge_set(f"{self.metric_prefix}.queue_depth", depth)
        return sig, batch

    # -- lifecycle -----------------------------------------------------------
    def close(self, drain: bool = True):
        with self._cond:
            self._closed = True
            if not drain:
                for req in self._items:
                    req.fail(EngineClosedError(
                        "serving engine shut down before this request "
                        "was served"))
                self._items.clear()
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def depth(self) -> int:
        with self._cond:
            return len(self._items)
