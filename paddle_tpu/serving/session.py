"""Decode-session journal — the state a generation needs to survive the
death of the replica running it.

A decode replica is pure state: the KV pages are rebuildable from the
token ids (chunked prefill is bitwise-identical to the cold run by
construction — serving/decode.py), and sampling is a pure function of
(logits bits, per-request RandomState). So the ONLY durable facts a
generation owns are tiny and host-side: the prompt, the accepted token
ids, the sampler RNG state after those draws, and the deadline
remainder. This module is that record plus the router-side store it
replicates into.

Protocol (reference analog: the Fluid pserver re-sends a dead trainer's
params — here the ROUTER is the survivor that re-seeds the work):

* The engine snapshots every session-carrying request at step-boundary
  cadence (FLAGS_decode_journal_stride) and hands the batch to its
  ``journal_sink`` — in-process a plain callable, cross-process an HTTP
  POST to the router's ``/v1/session/journal``.
* On decode-replica death the router rebuilds the submit from the last
  snapshot: prompt + accepted-so-far as the new prefill prompt, RNG
  state restored verbatim, ``max_new_tokens`` reduced by the accepted
  count, deadline set to the journaled remainder. The survivor's
  prefill either prefix-hits the store (warm) or chunk-re-prefills
  (cold); either way the resumed tail is bitwise-identical to the
  uninterrupted run (pinned by tests/test_orchestrator.py across
  greedy/sampled x fp32/int8 x PT_PALLAS off/interpret).
* The router concatenates journaled accepted tokens with the resumed
  tail, so the client sees ONE uninterrupted token stream.

Telemetry: session.journaled / session.failovers / session.resumed /
session.resumed_tokens / session.journal_errors / session.evicted —
rendered by tools/perf_report.py's "Sessions" section.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import telemetry
from ..core.flags import flag as _flag


def pack_rng_state(rng: Optional[np.random.RandomState]) -> Optional[list]:
    """np.random.RandomState -> JSON-able state. The MT19937 key vector
    rides as a plain int list — 624 words, small next to the KV pages it
    replaces."""
    if rng is None:
        return None
    name, key, pos, has_gauss, cached = rng.get_state()
    return [str(name), [int(x) for x in key], int(pos), int(has_gauss),
            float(cached)]


def unpack_rng_state(state) -> Optional[np.random.RandomState]:
    """Inverse of pack_rng_state; None passes through (greedy sessions
    journal no RNG)."""
    if state is None:
        return None
    name, key, pos, has_gauss, cached = state
    rng = np.random.RandomState()
    rng.set_state((str(name), np.asarray(key, np.uint32), int(pos),
                   int(has_gauss), float(cached)))
    return rng


def resume_args(record: Dict[str, Any]) -> Dict[str, Any]:
    """Journal record -> the kwargs of the re-admission submit. The
    resumed request generates only the REMAINING tokens; the caller
    (router) prepends ``record['accepted']`` to the resumed tail."""
    accepted = [int(t) for t in record.get("accepted", [])]
    out = {
        "prompt_ids": [int(t) for t in record["prompt"]],
        "prior_tokens": accepted,
        "max_new_tokens": int(record["max_new_total"]) - len(accepted),
        "temperature": float(record.get("temperature", 0.0)),
        "seed": record.get("seed"),
        "rng_state": record.get("rng_state"),
        "stop_at_eos": bool(record.get("stop_at_eos", True)),
        "request_id": record.get("request_id"),
    }
    rem = record.get("deadline_remaining_ms")
    if rem is not None:
        out["deadline_ms"] = max(1.0, float(rem))
    return out


class SessionJournal:
    """Router-side store of the latest snapshot per request id. Bounded
    LRU (FLAGS_router_session_capacity): completed sessions are popped
    by the router; abandoned ones age out at the capacity edge
    (session.evicted)."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(_flag("router_session_capacity")
                            if capacity is None else capacity)
        self._records: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()

    def update(self, records: List[Dict[str, Any]]) -> int:
        """Install a batch of snapshots (one POST = one engine step).
        A snapshot with fewer accepted tokens than the stored one is a
        late duplicate from a previous replica life — dropped, the
        journal only moves forward."""
        n = 0
        with self._lock:
            for rec in records:
                rid = rec.get("request_id")
                if not rid:
                    continue
                old = self._records.get(rid)
                if old is not None and (len(old.get("accepted", ()))
                                        > len(rec.get("accepted", ()))):
                    continue
                self._records[rid] = rec
                self._records.move_to_end(rid)
                n += 1
            while self.capacity > 0 and len(self._records) > self.capacity:
                self._records.popitem(last=False)
                telemetry.counter_add("session.evicted", 1)
        if n:
            telemetry.counter_add("session.journaled", n)
        return n

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._records.get(request_id)
            return dict(rec) if rec is not None else None

    def pop(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._records.pop(request_id, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)
