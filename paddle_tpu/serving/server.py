"""HTTP + in-process front ends for the ServingEngine.

Stdlib-only on purpose (http.server + json): a paddle_tpu worker serves
traffic with zero extra dependencies, the same way tools/perf_report.py
renders logs anywhere. The reference's analog is the C++ inference
server samples around AnalysisPredictor; TF-Serving's REST surface is
the API shape being mirrored.

API:
    POST /v1/generate {"prompt_ids": [ints], "max_new_tokens"?,
                      "temperature"?, "seed"?, "deadline_ms"?}
             200 ->  {"tokens": [ints], "num_tokens", "ttft_ms",
                      "model_version", "latency_ms"} — the generative
                     decode plane (serving/decode.py) when a
                     decode_engine is attached; 429 carries
                     error_type "KVCacheExhaustedError" for the typed
                     would-OOM refusal
    POST /v1/infer   {"inputs": {name: nested lists},
                      "deadline_ms": optional float}
             200 ->  {"outputs": {name: nested lists}, "latency_ms": f,
                      "trace_id": str|null}
             400 bad request (missing/odd inputs)
             429 ServerOverloadedError (admission backpressure)
             503 EngineClosedError (draining / shut down)
             504 DeadlineExceededError
             500 handler failure (per-request, queue keeps serving)
    GET  /healthz    READINESS (health.py state machine): 200
                     {"status": "ok", ...} only when the replica can
                     serve NOW; 503 {"status": "starting"} during
                     warmup, "swapping" during a model swap,
                     "draining"/"stopped" during/after close — a router
                     or external LB polling it never routes to a cold or
                     dying replica
    GET  /livez      LIVENESS: 200 while the process/engine can still
                     make progress (any state but stopped), else 503
    POST /v1/admin/swap {"model_dir": path, "version": int?}
                     zero-downtime model swap: verify the dir's COMMIT
                     manifest when present (PR 5 protocol), build + warm
                     the new predictor on every bucket, atomically flip
                     (engine.swap_predictor) — old version serves until
                     the flip
    GET  /v1/stats   serving.* counters + request/batch latency
                     percentiles + rolling-window rates (engine.stats());
                     when FLAGS_cost_capture is on, a "memory" section
                     with per-warmed-bucket cost/memory footprints and
                     the composed HBM ledger (core/costmodel.py)
    GET  /metrics    Prometheus text exposition of the live registry —
                     cumulative counters, rolling-window rates and
                     p50/p95/p99 over FLAGS_metrics_window_s

Tracing: every /v1/infer request opens a root span (core/trace.py,
sampled by FLAGS_trace_sample_rate) whose context flows through the
admission queue into the engine's batch worker, so one trace_id links
request → queue-wait → batch-assembly → predictor-run. A client-supplied
``X-Request-Id`` header forces sampling and pins the trace id; the
response carries it back as ``trace_id`` + an ``X-Trace-Id`` header.

``serve()`` wires model dir → predictor → engine (with every-bucket
warmup) → bound HTTP server in one call; ``LocalClient`` is the
in-process twin the tier-1 tests and bench harness use (no sockets).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from ..core import incidents, telemetry, trace
from .admission import (DeadlineExceededError, EngineClosedError,
                        KVCacheExhaustedError, ServerOverloadedError)
from .engine import ServingConfig, ServingEngine


class LocalClient:
    """In-process client: same request/response shape as the HTTP front
    end (outputs keyed by fetch name) without the socket."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def infer(self, inputs: Dict[str, Any],
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        outs = self.engine.infer(inputs, deadline_ms=deadline_ms,
                                 timeout=timeout)
        return dict(zip(self.engine.fetch_names, outs))


def _coerce_inputs(engine: ServingEngine,
                   raw: Dict[str, Any]) -> Dict[str, np.ndarray]:
    specs = engine.predictor.feed_specs()
    feeds = {}
    for name, value in raw.items():
        dtype = specs.get(name, ((), "float32"))[1]
        feeds[name] = np.asarray(value, dtype=np.dtype(dtype))
    return feeds


class _Handler(BaseHTTPRequestHandler):
    # the engine is attached to the server object by make_http_server
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # silence per-request stderr spam
        pass

    def _reply(self, code: int, payload: Dict[str, Any],
               headers: Optional[Dict[str, str]] = None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        engine = self.server.engine or self.server.decode_engine
        if self.path == "/healthz":
            # READINESS: 200 iff this replica should receive traffic NOW
            snap = engine.health.snapshot(
                queue_depth=engine.queue.depth(),
                model_version=engine.version)
            self._reply(200 if snap["ready"] else 503, snap)
        elif self.path == "/livez":
            alive = engine.health.is_alive()
            self._reply(200 if alive else 503,
                        {"status": "alive" if alive else "stopped"})
        elif self.path == "/v1/stats":
            stats = self.server.engine.stats() \
                if self.server.engine is not None else {}
            if self.server.decode_engine is not None:
                # the generative plane's counters + KV-cache/pool ledger
                stats["decode"] = self.server.decode_engine.stats()
            # SLO watchdog firing states + incident totals — the plane's
            # "health" verdict next to the raw counters (core/incidents)
            stats["health"] = incidents.health()
            self._reply(200, stats)
        elif self.path == "/metrics":
            body = telemetry.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def _handle_swap(self, engine: ServingEngine):
        """POST /v1/admin/swap — the replica side of the cluster's
        zero-downtime rolling swap (serving/cluster.py drives it)."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length) or b"{}")
            model_dir = doc["model_dir"]
        except (ValueError, TypeError, KeyError) as e:
            self._reply(400, {"error": f"bad swap request: {e!r}"})
            return
        try:
            from .. import checkpoint as _ckpt
            from ..inference import AnalysisConfig, create_predictor

            version = doc.get("version")
            if os.path.exists(os.path.join(model_dir, _ckpt.MANIFEST_NAME)):
                manifest = _ckpt.verify_model_dir(model_dir)
                if version is None:
                    version = manifest.get("version")
            predictor = create_predictor(AnalysisConfig(model_dir))
            # reload_config: rebuild the ServingConfig from the CURRENT
            # flag surface and flip it with the predictor — the
            # autotuner's online A/B applies a candidate config to one
            # replica through the same warm-then-flip machinery
            config = None
            if doc.get("reload_config"):
                from .engine import ServingConfig

                config = ServingConfig()
            fresh = engine.swap_predictor(predictor, version=version,
                                          config=config)
        except Exception as e:   # verify/build/warm/injected failure:
            # the old predictor is still live — report, don't die
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {"status": "ok", "model_version": engine.version,
                          "warmup_compiles": fresh})

    def _handle_generate(self):
        """POST /v1/generate — the generative decode plane
        (serving/decode.py): {"prompt_ids": [ints], "max_new_tokens"?,
        "temperature"?, "seed"?, "deadline_ms"?} -> {"tokens": [ints],
        "num_tokens", "ttft_ms", "latency_ms", "model_version"}."""
        de = self.server.decode_engine
        if de is None:
            self._reply(404, {"error": "no decode engine attached — "
                                       "this replica serves /v1/infer "
                                       "only"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length) or b"{}")
            prompt = doc["prompt_ids"]
        except (ValueError, TypeError, KeyError) as e:
            self._reply(400, {"error": f"bad generate request: {e!r}"})
            return
        t0 = time.perf_counter()
        # session identity: body request_id wins, else the X-Request-Id
        # header the router forwards — either opts the generation into
        # journaling; prior_tokens/rng_state re-admit a journaled
        # session after its replica died (serving/session.py)
        request_id = (doc.get("request_id")
                      or self.headers.get("X-Request-Id"))
        try:
            req = de.submit(prompt,
                            max_new_tokens=doc.get("max_new_tokens"),
                            deadline_ms=doc.get("deadline_ms"),
                            temperature=float(doc.get("temperature", 0.0)),
                            seed=doc.get("seed"),
                            stop_at_eos=bool(doc.get("stop_at_eos", True)),
                            request_id=request_id,
                            prior_tokens=doc.get("prior_tokens"),
                            rng_state=doc.get("rng_state"))
            tokens = req.result()
        except ValueError as e:
            self._reply(400, {"error": str(e)})
        except KVCacheExhaustedError as e:
            # typed would-OOM refusal: the client must shrink or retry
            # against a bigger pool — 429 with the typed name
            self._reply(429, {"error": str(e),
                              "error_type": "KVCacheExhaustedError"})
        except ServerOverloadedError as e:
            self._reply(429, {"error": str(e)},
                        {"Retry-After": "0.05"})
        except EngineClosedError as e:
            self._reply(503, {"error": str(e)})
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
        except Exception as e:
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
        else:
            payload = {
                "tokens": np.asarray(tokens).tolist(),
                "num_tokens": int(np.asarray(tokens).size),
                "ttft_ms": round(req.ttft_ms, 3)
                if req.ttft_ms is not None else None,
                "model_version": de.version,
                "latency_ms": round((time.perf_counter() - t0) * 1e3, 3)}
            if request_id is not None:
                payload["request_id"] = request_id
            if doc.get("prior_tokens"):
                # resumed session: the tokens above are the TAIL only;
                # the router re-joins them with the journaled prefix
                payload["resumed"] = True
            self._reply(200, payload)

    def _handle_prefill(self):
        """POST /v1/prefill — the prefill tier of disaggregated serving
        (serving/disagg.py): {"prompt": [ints]} -> the serialized KV
        page shipment (application/octet-stream, versioned wire format
        with per-page CRCs). Decode-role replicas fetch this and
        install the pages instead of prefilling locally."""
        de = self.server.decode_engine
        if de is None:
            self._reply(404, {"error": "no decode engine attached — "
                                       "nothing to prefill here"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length) or b"{}")
            prompt = doc["prompt"]
        except (ValueError, TypeError, KeyError) as e:
            self._reply(400, {"error": f"bad prefill request: {e!r}"})
            return
        try:
            blob = de.submit_prefill(
                prompt, deadline_ms=doc.get("deadline_ms")).result()
        except ValueError as e:
            self._reply(400, {"error": str(e)})
        except KVCacheExhaustedError as e:
            self._reply(429, {"error": str(e),
                              "error_type": "KVCacheExhaustedError"})
        except ServerOverloadedError as e:
            self._reply(429, {"error": str(e)}, {"Retry-After": "0.05"})
        except EngineClosedError as e:
            self._reply(503, {"error": str(e)})
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
        except Exception as e:
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
        else:
            body = bytes(blob)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    def do_POST(self):
        engine: ServingEngine = self.server.engine
        if self.path == "/v1/generate":
            self._handle_generate()
            return
        if self.path == "/v1/prefill":
            self._handle_prefill()
            return
        if self.path == "/v1/admin/swap":
            self._handle_swap(engine)
            return
        if self.path != "/v1/infer":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        if engine is None:
            self._reply(404, {"error": "no micro-batching engine "
                                       "attached — this replica serves "
                                       "/v1/generate only"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length) or b"{}")
            feeds = _coerce_inputs(engine, doc.get("inputs") or {})
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"bad request body: {e}"})
            return
        # request root span: an X-Request-Id header pins the trace id and
        # forces sampling; otherwise FLAGS_trace_sample_rate decides. The
        # context captured by engine.submit() inside this block links the
        # whole queue → batch → predictor timeline to one trace_id
        rid = self.headers.get("X-Request-Id")
        code, payload, headers = 500, {"error": "unhandled"}, {}
        t0 = time.perf_counter()
        with trace.root_span("serving.http_request", trace_id=rid,
                             force=bool(rid), path=self.path) as tctx:
            served_version = None
            try:
                req = engine.submit(feeds,
                                    deadline_ms=doc.get("deadline_ms"))
                outs = req.result()
                served_version = req.served_version
            except ValueError as e:      # missing/ragged inputs
                code, payload = 400, {"error": str(e)}
            except ServerOverloadedError as e:
                code, payload = 429, {"error": str(e)}
                headers = {"Retry-After": "0.05"}
            except EngineClosedError as e:
                code, payload = 503, {"error": str(e)}
            except DeadlineExceededError as e:
                code, payload = 504, {"error": str(e)}
            except Exception as e:       # injected / handler failure
                code, payload = 500, {"error": f"{type(e).__name__}: {e}"}
            else:
                code = 200
                payload = {
                    "outputs": {n: np.asarray(o).tolist()
                                for n, o in zip(engine.fetch_names, outs)},
                    "model_version": served_version,
                    "latency_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3)}
        if code == 200 or tctx is not None:
            payload["trace_id"] = tctx.trace_id if tctx else None
        if tctx is not None:
            headers["X-Trace-Id"] = tctx.trace_id
        self._reply(code, payload, headers)


class ServingHTTPServer:
    """Bound-but-not-yet-serving HTTP wrapper; start()/shutdown() own the
    acceptor thread. port=0 binds an ephemeral port (tests, CI)."""

    def __init__(self, engine: Optional[ServingEngine],
                 host: str = "127.0.0.1", port: int = 0,
                 decode_engine=None):
        if engine is None and decode_engine is None:
            raise ValueError("ServingHTTPServer needs an engine and/or a "
                             "decode_engine")
        self.engine = engine
        self.decode_engine = decode_engine
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.engine = engine
        self._httpd.decode_engine = decode_engine
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingHTTPServer":
        if self._thread is None:
            # a serving surface is the canonical always-on process: arm
            # the SLO watchdog (FLAGS_slo_watchdog 'auto'); the engine
            # loops drive evaluation via incidents.tick()
            incidents.arm()
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="pt-serving-http", daemon=True)
            self._thread.start()
        return self

    def shutdown(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        incidents.disarm()


def serve(model_dir: str, host: str = "127.0.0.1", port: int = 0,
          config: Optional[ServingConfig] = None,
          warmup: bool = True) -> ServingHTTPServer:
    """model dir → predictor → warmed engine → started HTTP server."""
    from ..inference import AnalysisConfig, create_predictor

    predictor = create_predictor(AnalysisConfig(model_dir))
    engine = ServingEngine(predictor, config=config)
    engine.start(warmup=warmup)
    # production entry: the pt-incidents-watchdog thread keeps the SLO
    # rules evaluating even while the replica is idle
    incidents.start_watchdog()
    return ServingHTTPServer(engine, host=host, port=port).start()


def serve_decode(model_dir: str, host: str = "127.0.0.1", port: int = 0,
                 config=None, warmup: bool = True) -> ServingHTTPServer:
    """Decoder-LM dir (models/decoder_lm.save_decoder_lm) → started
    generative HTTP server (POST /v1/generate)."""
    from .decode import decode_engine_from_dir

    de = decode_engine_from_dir(model_dir, config=config)
    de.start(warmup=warmup)
    incidents.start_watchdog()
    return ServingHTTPServer(None, host=host, port=port,
                             decode_engine=de).start()
