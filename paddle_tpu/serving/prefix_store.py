"""Content-addressed prefix store — cross-request KV page sharing.

The vLLM/SGLang prefix-caching idea rebuilt on the PR 12 paged pool:
prompts are split into page-sized token blocks and each block is keyed
by ``(parent_hash, token_block)`` — a hash CHAIN, so a block's identity
pins the entire token prefix in front of it, not just its own tokens.
Two requests that share a system prompt resolve to the same chain of
blocks and therefore the same physical KV pages; the second request
skips prefill for the shared chunks entirely and recomputes only its
suffix through the page-chunked prefill program
(models/decoder_lm.py build_chunk_prefill_program).

Why sharing is bitwise-safe: shared pages are READ-ONLY to every
program. A chunk's prefill writes land only in the request's private
freshly-allocated pages (the lookup matches at most
``floor((L-1)/P)`` blocks, so the final prompt chunk — the one that
produces first-token logits — is always recomputed), and the decode
step writes generated tokens past the prompt, again into private
pages. The attention ops mask invalid positions to -1e9 before
softmax, which underflows to exactly 0.0 — so neither physical page
ids nor recycled-page garbage can perturb a single output bit
(tier-1 gated in tests/test_prefix_store.py).

Lifecycle:

- ``lookup(tokens)`` walks the chain, bumps each matched block's
  refcount, and returns the shared pages to splice into the page
  table (``kv.prefix_hits`` / ``kv.prefix_misses``, ``kv.bytes_saved``).
- ``insert(tokens, pages, ...)`` runs after a prefill: the store
  ADOPTS the request's full prompt pages as shared blocks (refcount 1,
  held by the inserting request). Registering a second child under a
  parent that already has one is a copy-on-write fork of the chain at
  the divergence point (``kv.cow_forks``) — the diverging request
  recomputed its own pages, so no page is ever cloned in place.
- ``release(blocks)`` at retirement drops refcounts; refcount-zero
  chains STAY cached (that is the cache) until ``reclaim`` evicts
  them LRU leaf-first under pool pressure (``kv.reclaims``).

Booked in the HBM ledger as ``mem.serving.kv_prefix_saved_bytes``
(costmodel.ledger "serving_kv_prefix_saved_bytes"): cumulative pool
bytes requests did NOT privately allocate thanks to a hit.
``kv.prefix_lookup`` is a fault-injection site (core/faults.py,
tools/chaos_check.py --prefix).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import costmodel, faults, telemetry
from ..core.analysis import lockdep
from .kv_cache import KVPagePool

ROOT_HASH = "root"


def _chain_hash(parent_hash: str, tokens: Sequence[int]) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent_hash.encode("utf-8"))
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode("utf-8"))
    return h.hexdigest()


def prefix_chain_hash(tokens: Sequence[int], page_size: int) -> str:
    """Hash of the FULL-page prefix chain of a prompt — the router's
    affinity key (serving/router.py route_generate): equal shared
    prefixes hash to the same decode replica, so a session's turns
    land where its KV pages already live."""
    h = ROOT_HASH
    n = len(tokens) // int(page_size)
    for b in range(n):
        h = _chain_hash(h, tokens[b * page_size:(b + 1) * page_size])
    return h


class _Block:
    __slots__ = ("hash", "parent", "tokens", "page", "refs", "children",
                 "last_used")

    def __init__(self, hash_: str, parent: str, tokens: Tuple[int, ...],
                 page: int):
        self.hash = hash_
        self.parent = parent
        self.tokens = tokens
        self.page = page
        self.refs = 0
        self.children: set = set()
        self.last_used = 0


class PrefixStore:
    """Hash-chained, refcounted block index over a KVPagePool.

    Owns the physical pages of every resident block (they are lent
    from the pool and returned only at eviction) — ``owned_pages()``
    feeds ``pool.audit`` so chaos runs can prove nothing leaked."""

    def __init__(self, pool: KVPagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._lock = lockdep.lock("serving.kv_prefix")
        self._blocks: Dict[str, _Block] = {}
        self._clock = 0
        self._bytes_saved = 0

    # -- introspection -------------------------------------------------------
    def owned_pages(self) -> List[int]:
        with self._lock:
            return [b.page for b in self._blocks.values()]

    def num_blocks(self) -> int:
        with self._lock:
            return len(self._blocks)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._blocks)
            shared = sum(1 for b in self._blocks.values() if b.refs > 1)
            idle = sum(1 for b in self._blocks.values() if b.refs == 0)
            saved = self._bytes_saved
        return {"blocks": n, "blocks_shared": shared, "blocks_idle": idle,
                "bytes_saved": saved,
                "block_bytes": self.pool._page_bytes}

    def _gauges(self):
        telemetry.gauge_set("kv.prefix_blocks", len(self._blocks))
        telemetry.gauge_set("mem.serving.kv_prefix_saved_bytes",
                            self._bytes_saved)

    # -- lookup --------------------------------------------------------------
    def lookup(self, tokens: Sequence[int]) -> Tuple[List[str], List[int]]:
        """Longest cached prefix of ``tokens``: returns (block hashes,
        physical pages), refcounts bumped — caller MUST ``release`` the
        hashes at retirement. Matches at most ``floor((L-1)/P)`` blocks
        so the final prompt chunk is always recomputed (it yields the
        first-token logits). ``kv.prefix_lookup`` faults inject here —
        a failure is a per-request error, no refcount moves."""
        faults.maybe_fail("kv.prefix_lookup", tokens=len(tokens))
        P = self.page_size
        max_blocks = max(0, (len(tokens) - 1) // P)
        hashes: List[str] = []
        pages: List[int] = []
        with self._lock:
            self._clock += 1
            parent = ROOT_HASH
            for b in range(max_blocks):
                blk_tokens = tuple(int(t) for t in
                                   tokens[b * P:(b + 1) * P])
                h = _chain_hash(parent, blk_tokens)
                blk = self._blocks.get(h)
                if blk is None:
                    break
                blk.refs += 1
                blk.last_used = self._clock
                hashes.append(h)
                pages.append(blk.page)
                parent = h
            if hashes:
                self._bytes_saved += len(hashes) * self.pool._page_bytes
            saved_now = len(hashes) * self.pool._page_bytes
            self._gauges()
        if hashes:
            telemetry.counter_add("kv.prefix_hits", 1, blocks=len(hashes))
            telemetry.counter_add("kv.bytes_saved", saved_now)
        else:
            telemetry.counter_add("kv.prefix_misses", 1)
        return hashes, pages

    # -- insert --------------------------------------------------------------
    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               start_block: int = 0) -> Tuple[List[str], List[int]]:
        """Adopt a freshly prefilled prompt's FULL pages as shared
        blocks. ``pages`` are the request's prompt pages (page index i
        holds global tokens [i*P, (i+1)*P)); blocks before
        ``start_block`` were already acquired by lookup and are
        skipped. Only pages strictly before the page receiving decode
        writes are adoptable: ``floor(L/P)`` blocks total.

        Returns (hashes newly held by this request, the CANONICAL page
        per inserted block). The store adopts the candidate pages; the
        caller must repoint its page table at the canonical pages and
        drop them from its private list. On a duplicate insert (two
        racing cold requests with the same prompt) the resident block
        wins: its page is the canonical one and the redundant
        candidate page goes straight back to the pool."""
        P = self.page_size
        n_full = len(tokens) // P
        held: List[str] = []
        canonical: List[int] = []
        to_free: List[int] = []
        cow = 0
        with self._lock:
            self._clock += 1
            parent = ROOT_HASH
            for b in range(n_full):
                blk_tokens = tuple(int(t) for t in
                                   tokens[b * P:(b + 1) * P])
                h = _chain_hash(parent, blk_tokens)
                if b >= start_block:
                    blk = self._blocks.get(h)
                    if blk is None:
                        blk = _Block(h, parent, blk_tokens, int(pages[b]))
                        self._blocks[h] = blk
                        par = self._blocks.get(parent)
                        if par is not None:
                            if par.children:
                                cow += 1
                            par.children.add(h)
                    else:
                        # duplicate chain: the resident block wins, the
                        # candidate page is redundant
                        if blk.page != int(pages[b]):
                            to_free.append(int(pages[b]))
                    blk.refs += 1
                    blk.last_used = self._clock
                    held.append(h)
                    canonical.append(blk.page)
                parent = h
            self._gauges()
        if to_free:
            self.pool.free(to_free)
        if cow:
            telemetry.counter_add("kv.cow_forks", cow)
        return held, canonical

    # -- release / reclaim ---------------------------------------------------
    def release(self, hashes: Sequence[str]):
        """Drop one reference per hash (request retirement). Blocks at
        refcount zero remain resident — eviction is reclaim's job."""
        with self._lock:
            for h in hashes:
                blk = self._blocks.get(h)
                if blk is None:
                    raise AssertionError(
                        f"prefix store corruption: releasing unknown "
                        f"block {h}")
                if blk.refs <= 0:
                    raise AssertionError(
                        f"prefix store corruption: double release of "
                        f"block {h}")
                blk.refs -= 1

    def reclaim(self, n_pages: int) -> int:
        """Evict up to ``n_pages`` pages of refcount-zero LEAF blocks,
        LRU first, returning their pages to the pool. Leaf-only keeps
        every resident chain reachable from the root — an interior
        block with a cached child must outlive it. Returns pages
        actually freed (``kv.reclaims``)."""
        freed: List[int] = []
        with self._lock:
            while len(freed) < n_pages:
                victims = [b for b in self._blocks.values()
                           if b.refs == 0 and not b.children]
                if not victims:
                    break
                blk = min(victims, key=lambda b: b.last_used)
                del self._blocks[blk.hash]
                par = self._blocks.get(blk.parent)
                if par is not None:
                    par.children.discard(blk.hash)
                freed.append(blk.page)
            self._gauges()
        if freed:
            self.pool.free(freed)
            telemetry.counter_add("kv.reclaims", 1, pages=len(freed))
        return len(freed)
