"""Replica health — the liveness/readiness distinction serving planes
route on.

Kubernetes got this right and every serving mesh copied it: *liveness*
("the process is up and its loop can still make progress") and
*readiness* ("route traffic here NOW") are different questions with
different consumers. A replica warming its jit buckets is alive but not
ready; a replica draining its queue for shutdown or swapping model
versions is alive, still answering in-flight work, but must stop
receiving new requests. The PR 4 ``/healthz`` answered ``ok``
unconditionally — a router (or any external LB) polling it would happily
route to a cold or dying replica. This module is the small state machine
behind the fixed endpoint:

    STARTING --start()+warmup--> READY
    READY    --swap begins-----> SWAPPING --swap done--> READY
    READY    --close()---------> DRAINING --joined-----> STOPPED

Readiness is READY only. Liveness is everything but STOPPED. The HTTP
surface maps readiness to ``/healthz`` (200 ``{"status": "ok"}`` /
503 ``{"status": "starting"|"swapping"|"draining"|"stopped"}``) and
liveness to ``/livez``, so an LB that only understands one endpoint gets
the conservative answer and the router gets both.

State flips are announced on the ``serving.ready`` gauge (0/1) so the
live-metrics plane shows readiness transitions next to queue depth.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ..core import telemetry
from ..core.analysis import lockdep

STARTING = "starting"
READY = "ok"            # the wire string /healthz always reported when up
SWAPPING = "swapping"
DRAINING = "draining"
STOPPED = "stopped"

_LIVE = (STARTING, READY, SWAPPING, DRAINING)


class HealthState:
    """Thread-safe replica health: one current state + transition log."""

    def __init__(self, state: str = STARTING, name: str = ""):
        self._lock = lockdep.lock("serving.health")
        self._state = state
        self._since = time.time()
        self.name = name

    # -- transitions ---------------------------------------------------------
    def set(self, state: str):
        with self._lock:
            if state == self._state:
                return
            prev, self._state = self._state, state
            self._since = time.time()
        telemetry.gauge_set("serving.ready", 1 if state == READY else 0)
        telemetry.counter_add("serving.health_transitions", 1,
                             frm=prev, to=state, replica=self.name)

    # -- queries -------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def is_ready(self) -> bool:
        return self.state == READY

    def is_alive(self) -> bool:
        return self.state in _LIVE

    def snapshot(self, **extra: Any) -> Dict[str, Any]:
        with self._lock:
            state, since = self._state, self._since
        out = {"status": state, "ready": state == READY,
               "alive": state in _LIVE,
               "since_s": round(time.time() - since, 3)}
        out.update(extra)
        return out


class ReadyGate:
    """Scoped not-ready marker: hold a state (SWAPPING/DRAINING) for the
    duration of a block, then restore the entry state — but only if no
    OTHER transition happened meanwhile (a close() arriving mid-swap
    moves to DRAINING/STOPPED and must win; a finished swap must not
    resurrect a draining replica)."""

    def __init__(self, health: HealthState, state: str):
        self.health = health
        self.state = state
        self._was: Optional[str] = None

    def __enter__(self):
        self._was = self.health.state
        self.health.set(self.state)
        return self

    def __exit__(self, et, ev, tb):
        if self.health.state == self.state and self._was is not None:
            self.health.set(self._was)
        return False
