"""DecodeEngine — continuous-batching autoregressive generation over a
paged KV cache.

The ServingEngine (engine.py) micro-batches single-shot predictors; this
engine is its generative twin for the workload that dominates LLM
serving traffic: many concurrent requests each producing tokens one
step at a time. Orca-style continuous batching + vLLM-style paged KV
caching, on the repo's frozen-program stack:

* **Phase split.** An admitted request first runs ONE prefill program
  (models/decoder_lm.build_prefill_program, padded to a prompt-length
  bucket) that writes the whole prompt's K/V into its pool pages and
  yields the first sampled token; from then on it only rides the shared
  decode step.
* **Continuous batching.** Decode state lives in a slot array of
  ``max_slots`` recycled slots. Every iteration the scheduler retires
  finished/expired sequences (freeing their pages) and admits queued
  requests into the vacated slots at the step boundary — no
  drain-and-refill: a long generation never holds the batch hostage for
  a short one. One ``jax.jit`` entry per slot-array bucket
  (FLAGS_decode_buckets; the default is a single fixed bucket of
  ``max_slots``, which ALSO pins the step shapes — per-row math is then
  independent of occupancy, keeping continuous-batched generations
  BITWISE-identical to sequential one-request-at-a-time decode).
* **Paged KV cache.** Pages come from the preallocated
  ``KVPagePool`` (kv_cache.py); the pool arrays are threaded through
  the step program and donated to the jit so XLA updates them in place.
  Pool bytes book into the PR 10 HBM ledger (``mem.serving.kv_*``) and
  a request whose worst-case page need can never fit is refused at
  submit with a typed ``KVCacheExhaustedError`` — admission control,
  not a device OOM.
* **int8 weight-only serving** as a first-class config
  (``weight_quant="int8"`` / FLAGS_decode_weight_quant): dense weights
  are stored int8 with per-output-channel scales and dequantized through
  ops/quant_ops.py ``dequantize_weight`` inside the programs.
* **Deadline-aware scheduling** reusing serving/admission.py: queued
  requests expire at dequeue (AdmissionQueue.poll), running requests
  are checked at STEP granularity — an expired generation retires
  mid-flight with ``DeadlineExceededError`` and frees its pages without
  draining the batch.

Sampling happens host-side per row (greedy argmax, or temperature
sampling driven by a per-request pinned ``np.random.RandomState``), so
token selection is a pure function of the row's logits bits and the
request's own seed — scheduling cannot perturb it.

Fault sites (core/faults.py, tools/chaos_check.py --decode):
``decode.step`` fails the in-flight step (every affected request gets a
per-request error, pages are freed, the queue keeps moving) and
``decode.kv_alloc`` fails one request's page allocation.

Telemetry: decode.requests/rejects/deadline_expired (admission),
decode.prefills / prefill_tokens / steps / tokens / retired / errors /
kv_refusals / kv_pages_allocated / kv_pages_freed counters,
decode.prefill_ms + decode.step_ms timers, decode.batch_occupancy
histogram, decode.active_slots + decode.queue_depth +
mem.serving.kv_* gauges — rendered by tools/perf_report.py's "Decode"
section and /v1/stats.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core import costmodel, faults, incidents, telemetry
from ..core import flags as _flags
from ..core.flags import flag as _flag
from ..models.decoder_lm import (DecoderLMConfig,
                                 build_chunk_prefill_program,
                                 build_prefill_program,
                                 build_step_program, decoder_lm_params,
                                 quantize_decoder_lm_params)
from .admission import (AdmissionQueue, DeadlineExceededError,
                        EngineClosedError, InferenceRequest,
                        KVCacheExhaustedError, ServingError)
from .health import DRAINING, READY, STOPPED, HealthState
from .kv_cache import KVPagePool
from .prefix_store import PrefixStore


def _pow2_ladder(lo: int, hi: int) -> List[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return sorted(set(out))


class DecodeConfig:
    """Decode-engine knobs; defaults come from the FLAGS_decode_*
    registry. ``continuous=False`` turns the scheduler into the
    drain-and-refill static-batching baseline (admit a wave, run it to
    completion, only then admit the next) — the control arm of
    tools/bench_serving.py --generate."""

    def __init__(self, max_slots: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 max_new_tokens: Optional[int] = None,
                 weight_quant: Optional[str] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 continuous: bool = True,
                 prefix_cache: Optional[bool] = None,
                 role: Optional[str] = None,
                 prefill_urls: Optional[Any] = None):
        self.max_slots = int(_flag("decode_max_slots") if max_slots is None
                             else max_slots)
        # strict typed parse (core/flags.py): zero-valued or
        # non-monotonic lists raise BucketConfigError; the set must end
        # exactly at max_slots (the fixed-step-shape contract).
        # default: ONE fixed bucket — constant step shapes keep
        # continuous batching bitwise-identical to sequential decode
        if buckets is None:
            buckets = _flags.parse_buckets(_flag("decode_buckets"),
                                           "FLAGS_decode_buckets",
                                           cover=self.max_slots,
                                           cover_exact=True)
        else:
            buckets = _flags.parse_buckets(buckets, "buckets",
                                           cover=self.max_slots,
                                           cover_exact=True)
        self.buckets = buckets or [self.max_slots]
        self.page_size = int(_flag("decode_page_size") if page_size is None
                             else page_size)
        self.kv_pages = int(_flag("decode_kv_pages") if kv_pages is None
                            else kv_pages)
        self.max_queue_depth = int(
            _flag("decode_max_queue_depth") if max_queue_depth is None
            else max_queue_depth)
        self.default_deadline_ms = float(
            _flag("decode_default_deadline_ms") if default_deadline_ms is None
            else default_deadline_ms)
        self.max_new_tokens = int(
            _flag("decode_max_new_tokens") if max_new_tokens is None
            else max_new_tokens)
        self.weight_quant = str(
            _flag("decode_weight_quant") if weight_quant is None
            else weight_quant).lower()
        if self.weight_quant not in ("none", "int8"):
            raise ValueError(f"decode weight_quant must be 'none' or "
                             f"'int8', got {self.weight_quant!r}")
        self.prefill_buckets = sorted(set(int(b) for b in prefill_buckets)) \
            if prefill_buckets else None   # None -> pow2 up to max_seq_len
        self.continuous = bool(continuous)
        # prefix sharing + disaggregated-serving role (serving/
        # prefix_store.py, serving/disagg.py)
        self.prefix_cache = bool(
            _flag("decode_prefix_cache") if prefix_cache is None
            else prefix_cache)
        self.role = str(_flag("decode_role") if role is None
                        else role).lower()
        if self.role not in ("unified", "prefill", "decode"):
            raise ValueError(f"decode role must be 'unified', 'prefill' "
                             f"or 'decode', got {self.role!r}")
        if prefill_urls is None:
            prefill_urls = _flag("disagg_prefill_urls")
        if isinstance(prefill_urls, str):
            prefill_urls = [u.strip() for u in prefill_urls.split(",")
                            if u.strip()]
        self.prefill_urls = [str(u) for u in prefill_urls]

    def bucket(self, active: int) -> int:
        for b in self.buckets:
            if active <= b:
                return b
        return self.buckets[-1]


class GenerationRequest(InferenceRequest):
    """One queued/running generation: prompt + sampling params + the
    engine-side decode state. Rides the shared AdmissionQueue (deadline
    at dequeue, typed backpressure); ``result()`` returns the generated
    token ids as an int32 array. ``ttft_ms`` / ``token_walls`` expose
    time-to-first-token and per-token arrival times for the bench
    harness."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "seed",
                 "eos_id", "tokens", "token_walls", "t_submit", "t_first",
                 "pages", "table_row", "pos_next", "last_token",
                 "shared_blocks", "_rng", "session_id", "prior", "seq",
                 "stop_at_eos")

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 deadline: Optional[float], temperature: float = 0.0,
                 seed: Optional[int] = None, eos_id: Optional[int] = None,
                 trace: Optional[Any] = None,
                 session_id: Optional[str] = None,
                 prior: Optional[np.ndarray] = None):
        super().__init__({"prompt": prompt}, 1, deadline, trace=trace)
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.seed = seed
        self.eos_id = eos_id
        self.stop_at_eos = eos_id is not None
        self.tokens: List[int] = []
        self.token_walls: List[float] = []
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        # session-failover identity (serving/session.py): ``prior`` is
        # the accepted tokens from a previous replica life — the engine
        # prefills ``seq`` (prompt + prior) and generates only the
        # remainder; the router re-joins the full stream
        self.session_id = session_id
        self.prior = (np.zeros(0, np.int32) if prior is None
                      else np.asarray(prior, np.int32).reshape(-1))
        self.seq = (prompt if self.prior.size == 0
                    else np.concatenate([prompt, self.prior]))
        # engine-side slot state (worker-thread-owned once admitted)
        self.pages: List[int] = []
        self.table_row: Optional[np.ndarray] = None
        self.pos_next = 0
        self.last_token = 0
        # prefix-store block hashes this request holds a reference on
        # (serving/prefix_store.py) — released at retirement
        self.shared_blocks: List[str] = []
        self._rng = np.random.RandomState(seed) if seed is not None \
            else None

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return (self.t_first - self.t_submit) * 1e3

    def sample(self, logits_row: np.ndarray) -> int:
        """Host-side token choice — a pure function of the row's logits
        bits and this request's own RNG stream, so batching/scheduling
        cannot perturb it. Greedy when temperature <= 0 (argmax, lowest
        index on ties); else softmax-at-temperature inverse-CDF driven
        by the pinned per-request RandomState."""
        if self.temperature <= 0.0:
            return int(np.argmax(logits_row))
        if self._rng is None:
            raise ValueError("sampled decoding (temperature > 0) needs a "
                             "per-request seed for reproducible serving")
        z = logits_row.astype(np.float64) / self.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        # clamp: a draw past the fp cumsum tail must not index vocab+1
        idx = np.searchsorted(np.cumsum(p), self._rng.random_sample())
        return int(min(idx, len(p) - 1))

    def finished(self) -> bool:
        return bool(self.tokens) and (
            len(self.tokens) >= self.max_new_tokens
            or (self.eos_id is not None and self.tokens[-1] == self.eos_id))

    def journal_record(self, page_size: int,
                       now: Optional[float] = None) -> Dict[str, Any]:
        """Snapshot everything a survivor needs to continue this
        generation bitwise-identically (serving/session.py): the prompt
        (plus its page-chain hash for affinity), EVERY accepted token —
        prior lives included — the sampler RNG state after those draws,
        and the deadline remainder. Engine-thread-only (reads _rng)."""
        from .prefix_store import prefix_chain_hash

        rem = None
        if self.deadline is not None:
            rem = max(0.0, (self.deadline
                            - (time.monotonic() if now is None else now))
                      * 1e3)
        from .session import pack_rng_state

        return {
            "request_id": self.session_id,
            "prompt": [int(t) for t in self.prompt],
            "prefix_hash": prefix_chain_hash(self.prompt, page_size),
            "accepted": [int(t) for t in self.prior] + list(self.tokens),
            "max_new_total": int(self.prior.size) + self.max_new_tokens,
            "temperature": self.temperature,
            "seed": self.seed,
            "stop_at_eos": self.stop_at_eos,
            "rng_state": pack_rng_state(self._rng)
            if self.temperature > 0 else None,
            "deadline_remaining_ms": rem,
        }


class ShipPrefillRequest(InferenceRequest):
    """Disaggregated-serving prefill work item (serving/disagg.py): a
    prefill-tier replica runs the prompt's prefill, reads the finished
    KV pages back to host, and resolves with the serialized shipment
    bytes (versioned wire format, per-page CRC). Rides the same
    AdmissionQueue as generations so every program run stays on the
    worker thread that owns the donated pool arrays."""

    __slots__ = ("prompt",)

    def __init__(self, prompt: np.ndarray, deadline: Optional[float]):
        super().__init__({"prompt": prompt}, 1, deadline)
        self.prompt = prompt


class DecodeEngine:
    """Thread-safe generative front end over a frozen decoder-LM param
    set. Lifecycle mirrors ServingEngine: ``start()`` → concurrent
    ``submit``/``generate`` → ``close(drain=True)``. One worker thread
    owns the slot array, the pools and every program run."""

    def __init__(self, model_cfg: DecoderLMConfig, params: Dict[str, Any],
                 config: Optional[DecodeConfig] = None, version: int = 0):
        import jax.numpy as jnp

        self.model_cfg = model_cfg
        self.config = config or DecodeConfig()
        if self.config.weight_quant == "int8":
            params = quantize_decoder_lm_params(params, model_cfg)
            telemetry.counter_add("decode.int8_weight_tensors",
                                  sum(1 for n in params
                                      if n.endswith("_w_i8")))
        self._params = {n: jnp.asarray(v) for n, v in params.items()}
        self.pool = KVPagePool(model_cfg.n_layers, self.config.kv_pages,
                               self.config.page_size, model_cfg.d_model)
        self._pools = self.pool.make_arrays()
        self._mp = -(-model_cfg.max_seq_len // self.config.page_size)
        self.queue = AdmissionQueue(self.config.max_queue_depth,
                                    self.config.default_deadline_ms,
                                    metric_prefix="decode")
        if self.config.prefill_buckets is None:
            self.config.prefill_buckets = _pow2_ladder(
                min(8, model_cfg.max_seq_len), model_cfg.max_seq_len)
        # content-addressed prefix sharing: admission consults the store
        # for the longest cached prefix and prefills only the suffix
        # through the page-chunked prefill program
        self.prefix_store = PrefixStore(self.pool) \
            if self.config.prefix_cache else None
        self._active: List[GenerationRequest] = []
        self._entries: Dict[Any, Any] = {}   # (phase, bucket) -> jitted fn
        self._thread: Optional[threading.Thread] = None
        self.health = HealthState()
        self.version = int(version)
        # session-failover journal (serving/session.py): a callable
        # taking a list of journal records — in-process the router's
        # SessionJournal.update, cross-process an HTTP POST. None (the
        # default) disables journaling entirely.
        self.journal_sink = None
        self._journal_stride = int(_flag("decode_journal_stride"))

    # -- client surface ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, seed: Optional[int] = None,
               stop_at_eos: bool = True,
               request_id: Optional[str] = None,
               prior_tokens: Optional[Sequence[int]] = None,
               rng_state: Optional[Any] = None) -> GenerationRequest:
        """Enqueue one generation (non-blocking). ``prompt`` is a 1-D
        int token-id array. Raises ValueError (malformed / over the
        model length), KVCacheExhaustedError (can never fit the KV
        pool), ServerOverloadedError, EngineClosedError.

        ``request_id`` opts the request into session journaling
        (serving/session.py). ``prior_tokens``/``rng_state`` re-admit a
        journaled session after its replica died: the engine prefills
        prompt+prior (prefix-hit or chunked cold re-prefill — bitwise
        the same KV either way), restores the sampler RNG mid-stream
        and generates only the remaining ``max_new_tokens``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt needs at least one token")
        prior = (np.zeros(0, np.int32) if prior_tokens is None
                 else np.asarray(prior_tokens, np.int32).reshape(-1))
        if max_new_tokens is None:
            max_new_tokens = self.config.max_new_tokens
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        total = int(prompt.size) + int(prior.size) + max_new_tokens
        if total > self.model_cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size + prior.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's max_seq_len "
                f"({self.model_cfg.max_seq_len})")
        # typed would-OOM refusal BEFORE the request enters the queue
        self.pool.check_fits(total)
        req = GenerationRequest(
            prompt, max_new_tokens, self.queue.deadline_for(deadline_ms),
            temperature=temperature, seed=seed,
            eos_id=self.model_cfg.eos_id if stop_at_eos else None,
            session_id=request_id, prior=prior)
        if rng_state is not None:
            from .session import unpack_rng_state

            req._rng = unpack_rng_state(rng_state)
        if prior.size:
            telemetry.counter_add("session.resumed", 1)
            telemetry.counter_add("session.resumed_tokens",
                                  int(prior.size))
        self.queue.submit_request(req)
        return req

    def generate(self, prompt, timeout: Optional[float] = None,
                 **kw) -> np.ndarray:
        """Blocking submit-and-wait; returns the generated int32 ids."""
        return self.submit(prompt, **kw).result(timeout)

    def submit_prefill(self, prompt,
                       deadline_ms: Optional[float] = None
                       ) -> ShipPrefillRequest:
        """Disaggregated serving (serving/disagg.py): enqueue a
        prefill-and-ship work item. ``result()`` returns the serialized
        KV page shipment bytes for the prompt."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt needs at least one token")
        if int(prompt.size) > self.model_cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) exceeds the model's max_seq_len "
                f"({self.model_cfg.max_seq_len})")
        self.pool.check_fits(int(prompt.size))
        req = ShipPrefillRequest(prompt,
                                 self.queue.deadline_for(deadline_ms))
        self.queue.submit_request(req)
        return req

    def stats(self) -> Dict[str, Any]:
        """decode.* counters + KV pool accounting + latency percentiles
        + rolling-window token rate — the /v1/stats "decode" payload."""
        c = telemetry.counters()
        out = {k.split(".", 1)[1]: int(v) for k, v in c.items()
               if k.startswith("decode.") and isinstance(v, (int, float))}
        out["queue_depth"] = self.queue.depth()
        out["model_version"] = self.version
        out["status"] = self.health.state
        out["role"] = self.config.role
        out["kv_cache"] = self.pool.stats()
        if self.prefix_store is not None:
            out["prefix_store"] = self.prefix_store.stats()
            out["prefix_store"].update(
                {k.split(".", 1)[1]: int(v) for k, v in c.items()
                 if k.startswith("kv.") and isinstance(v, (int, float))})
        dis = {k.split(".", 1)[1]: int(v) for k, v in c.items()
               if k.startswith("disagg.") and isinstance(v, (int, float))}
        if dis:
            out["disagg"] = dis
        from ..ops import pallas as _pallas

        # per-kernel dispatch/fallback counters (counted at lowering
        # time) + the live kernel fingerprint — which code path this
        # engine's programs actually compiled
        out["pallas"] = dict(
            {k.split(".", 1)[1]: int(v) for k, v in c.items()
             if k.startswith("pallas.") and isinstance(v, (int, float))},
            kernels=_pallas.kernels_fingerprint())
        hists = telemetry.snapshot()["hists"]
        for key in ("decode.step_ms", "decode.prefill_ms",
                    "decode.request_ms"):
            h = hists.get(key)
            if h:
                out[key.split(".", 1)[1]] = {
                    "count": h["count"], "avg": h["avg"], "p50": h["p50"],
                    "p95": h["p95"], "p99": h["p99"], "max": h["max"]}
        occ = hists.get("decode.batch_occupancy")
        if occ:
            out["batch_occupancy"] = {"avg": occ["avg"], "p50": occ["p50"]}
        win = telemetry.windowed()
        wout = {"seconds": win["window_s"]}
        for name, key in (("decode.tokens", "tokens_per_s"),
                          ("decode.steps", "steps_per_s")):
            wc = win["counters"].get(name)
            if wc:
                wout[key] = wc["rate"]
        out["window"] = wout
        return out

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup: bool = False) -> "DecodeEngine":
        if self._thread is not None:
            return self
        if self.queue.closed:
            raise EngineClosedError("decode engine was closed; "
                                    "build a new one")
        if warmup:
            self.warmup()
        self._thread = threading.Thread(target=self._loop,
                                        name="pt-decode-engine",
                                        daemon=True)
        self._thread.start()
        self.health.set(READY)
        return self

    def warmup(self) -> int:
        """Pre-compile every decode bucket and every prefill bucket so
        no request ever pays a compile mid-load (a mid-generation
        compile stalls the WHOLE slot array, not just one request).
        Returns the number of fresh compiles."""
        before = telemetry.counter_get("decode.compiles")
        for b in self.config.buckets:
            self._entry("step", b)
        for b in self.config.prefill_buckets:
            self._entry("prefill", b)
        if self.prefix_store is not None:
            # the ONE chunked-prefill entry (chunk length == page size)
            self._entry("chunk", self.config.page_size)
        return int(telemetry.counter_get("decode.compiles") - before)

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        self.health.set(DRAINING)
        self.queue.close(drain=drain)
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.health.set(STOPPED)

    # -- program compilation -------------------------------------------------
    def _entry(self, phase: str, bucket: int):
        """One jitted (params, pools, feed) -> (logits, new_pools) entry
        per (phase, bucket), pools donated so XLA updates the KV arrays
        in place; compile wall time + XLA cost capture accounted like
        the predictor's cache."""
        key = (phase, bucket)
        entry = self._entries.get(key)
        if entry is not None:
            return entry
        import jax

        from ..core.executor import run_block

        cfg, cc = self.model_cfg, self.config
        if phase == "step":
            program, _feeds, _fetches = build_step_program(
                cfg, bucket, cc.kv_pages, cc.page_size, cc.weight_quant)
        elif phase == "chunk":
            program, _feeds, _fetches = build_chunk_prefill_program(
                cfg, 1, bucket, cc.kv_pages, cc.page_size, cc.weight_quant)
        else:
            program, _feeds, _fetches = build_prefill_program(
                cfg, 1, bucket, cc.kv_pages, cc.page_size, cc.weight_quant)
        block = program.global_block()
        pool_names = sorted(self._pools)

        def fn(params, pools, feed):
            env = dict(params)
            env.update(pools)
            env.update(feed)
            run_block(block, env)
            return env["logits"], {n: env[n + "_out"] for n in pool_names}

        from ..ops import pallas as _pallas

        entry = jax.jit(fn, donate_argnums=(1,))
        self._entries[key] = entry
        t0 = time.perf_counter()
        feed = self._zero_feed(phase, bucket)
        # the Pallas kernel fingerprint (PT_PALLAS mode + tile/chunk
        # geometry) keys the cost capture so flops/bytes attribute to
        # the kernel VARIANT actually compiled — the roofline verdict of
        # the stock gather+einsum lowering and the paged kernel are
        # different programs, not one blurred row
        pallas_fp = _pallas.kernels_fingerprint()
        if costmodel.capture_mode() != "off":
            costmodel.capture(
                lambda: entry.lower(self._params, dict(self._pools), feed),
                key_id=costmodel.key_id_for((phase, bucket,
                                             cc.weight_quant, pallas_fp)),
                kind="decode", program=f"{phase}_b{bucket}")
        # compile through a throwaway execution on zero feeds (the
        # predictor's measure-through-first-run discipline); FRESH pool
        # arrays, because donation consumes whatever is passed in
        entry(self._params, self.pool.make_arrays(), feed)
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        telemetry.counter_add("decode.compiles", 1)
        telemetry.event("compile", "decode", ms,
                        {"cause": "decode_bucket", "phase": phase,
                         "bucket": bucket,
                         "pallas_kernels": pallas_fp,
                         "cache_size": len(self._entries)})
        return entry

    def _zero_feed(self, phase: str, bucket: int):
        import jax.numpy as jnp

        if phase == "step":
            return {"tokens": jnp.zeros((bucket,), jnp.int32),
                    "positions": jnp.zeros((bucket,), jnp.int32),
                    "page_table": jnp.zeros((bucket, self._mp), jnp.int32)}
        oh = np.zeros((1, bucket), np.float32)
        oh[0, 0] = 1.0
        if phase == "chunk":
            return {"tokens": jnp.zeros((1, bucket), jnp.int32),
                    "positions": jnp.zeros((1, bucket), jnp.int32),
                    "chunk_start": jnp.zeros((1,), jnp.int32),
                    "lengths": jnp.ones((1,), jnp.int32),
                    "last_onehot": jnp.asarray(oh),
                    "page_table": jnp.zeros((1, self._mp), jnp.int32)}
        return {"tokens": jnp.zeros((1, bucket), jnp.int32),
                "lengths": jnp.ones((1,), jnp.int32),
                "last_onehot": jnp.asarray(oh),
                "page_table": jnp.zeros((1, self._mp), jnp.int32)}

    # -- scheduler loop ------------------------------------------------------
    def _loop(self):
        while True:
            if not self._active:
                has_work = self.queue.wait_for_work(0.05)
                if not has_work:
                    if self.queue.closed:
                        return
                    continue
            try:
                self._admit()
                if self._active:
                    self._run_step()
                    self._journal_tick()
            except BaseException as e:   # the loop must outlive any step
                telemetry.counter_add("decode.errors",
                                      max(1, len(self._active)),
                                      exc=type(e).__name__)
                err = e if isinstance(e, ServingError) else ServingError(
                    f"decode step failed: {e!r}")
                for req in self._active:
                    self._retire(req, error=err)
                self._active = []
            telemetry.gauge_set("decode.active_slots", len(self._active))
            # SLO watchdog hook (core/incidents.py): queue saturation /
            # step-time regression rules evaluate on the step cadence
            incidents.tick()

    def _admit(self):
        """Seat queued requests into free slots at the step boundary.
        Non-continuous (drain-and-refill baseline) only admits into an
        EMPTY slot array."""
        if not self.config.continuous and self._active:
            return
        free = self.config.max_slots - len(self._active)
        if free <= 0:
            return
        unseated: List[GenerationRequest] = []
        for req in self.queue.poll(free):
            if isinstance(req, ShipPrefillRequest):
                self._ship_prefill(req)
                continue
            # disaggregated decode role: try to install a shipped
            # prefill from the prefill tier; ANY failure (connection,
            # CRC reject) falls back to a local prefill
            if (self.config.role == "decode" and self.config.prefill_urls
                    and self._admit_shipped(req)):
                continue
            # prefix sharing: acquire the longest cached prefix chain;
            # a lookup fault is a per-request error, nothing acquired
            hashes: List[str] = []
            shared: List[int] = []
            if self.prefix_store is not None:
                try:
                    hashes, shared = self.prefix_store.lookup(req.seq)
                except Exception as e:
                    telemetry.counter_add("decode.errors", 1,
                                          exc=type(e).__name__)
                    req.fail(e if isinstance(e, ServingError)
                             else ServingError(
                                 f"prefix lookup failed: {e!r}"))
                    continue
            need = self.pool.pages_for_tokens(
                int(req.seq.size) + req.max_new_tokens) - len(hashes)
            try:
                pages = self.pool.try_alloc(need)
                if not pages and self.prefix_store is not None:
                    # ledger pressure: reclaim idle refcount-zero
                    # chains LRU-first, then retry once
                    short = need - self.pool.free_pages()
                    if short > 0 and self.prefix_store.reclaim(short):
                        pages = self.pool.try_alloc(need)
            except Exception as e:   # injected decode.kv_alloc fault
                if hashes:
                    self.prefix_store.release(hashes)
                telemetry.counter_add("decode.errors", 1,
                                      exc=type(e).__name__)
                req.fail(e if isinstance(e, ServingError) else ServingError(
                    f"KV page allocation failed: {e!r}"))
                continue
            if not pages:
                if hashes:
                    self.prefix_store.release(hashes)
                unseated.append(req)   # no headroom NOW — wait for frees
                continue
            try:
                self._prefill(req, pages, hashes, shared)
            except BaseException as e:
                self.pool.free(req.pages if req.pages else pages)
                req.pages = []
                if req.shared_blocks:
                    self.prefix_store.release(req.shared_blocks)
                    req.shared_blocks = []
                telemetry.counter_add("decode.errors", 1,
                                      exc=type(e).__name__)
                req.fail(e if isinstance(e, ServingError) else ServingError(
                    f"prefill failed: {e!r}"))
        self.queue.requeue(unseated)

    def _prefill(self, req: GenerationRequest, pages: List[int],
                 hashes: Optional[List[str]] = None,
                 shared: Optional[List[int]] = None):
        """PREFILL phase. With the prefix store on, EVERY prefill runs
        page-aligned chunks through the one chunked entry (a cache hit
        just skips the cached leading chunks — bitwise identity with
        the cold run holds by construction: same program, same fixed
        shape, same order). Otherwise the classic one-pass causal
        prefill over the padded prompt."""
        if self.prefix_store is not None:
            return self._prefill_chunked(req, pages, hashes or [],
                                         shared or [])
        import jax.numpy as jnp

        L = int(req.seq.size)
        bucket = next(b for b in self.config.prefill_buckets if b >= L)
        req.pages = pages
        row = np.zeros(self._mp, np.int32)
        row[:len(pages)] = pages
        req.table_row = row
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :L] = req.seq
        oh = np.zeros((1, bucket), np.float32)
        oh[0, L - 1] = 1.0
        feed = {"tokens": jnp.asarray(tokens),
                "lengths": jnp.asarray([L], jnp.int32),
                "last_onehot": jnp.asarray(oh),
                "page_table": jnp.asarray(row[None, :])}
        entry = self._entry("prefill", bucket)
        with telemetry.timer("decode.prefill_ms"):
            logits, self._pools = entry(self._params, self._pools, feed)
            logits = np.asarray(logits)
        telemetry.counter_add("decode.prefills", 1)
        telemetry.counter_add("decode.prefill_tokens", L)
        self._append_token(req, logits[0])
        req.pos_next = L
        if req.finished():
            self._retire(req)
        else:
            self._active.append(req)

    def _prefill_chunked(self, req: GenerationRequest, pages: List[int],
                         hashes: List[str], shared: List[int]):
        """Chunked prefill (prefix store on): the page table splices
        the ``len(hashes)`` shared prefix pages in front of the private
        pages, then each UNCACHED page-sized chunk runs through the one
        fixed-shape chunk entry. Writes land only in private pages (the
        lookup's match cap keeps the final chunk — the one producing
        first-token logits — always recomputed); afterwards the store
        adopts this prompt's full pages so the next request shares
        them."""
        import jax.numpy as jnp

        L = int(req.seq.size)
        P = self.config.page_size
        k = len(hashes)
        req.pages = pages
        req.shared_blocks = list(hashes)
        row = np.zeros(self._mp, np.int32)
        row[:k] = shared
        row[k:k + len(pages)] = pages
        req.table_row = row
        n_chunks = -(-L // P)
        entry = self._entry("chunk", P)
        logits = None
        with telemetry.timer("decode.prefill_ms"):
            for ci in range(k, n_chunks):
                lo = ci * P
                n = min(L, lo + P) - lo
                tokens = np.zeros((1, P), np.int32)
                tokens[0, :n] = req.seq[lo:lo + n]
                positions = np.clip(lo + np.arange(P, dtype=np.int32), 0,
                                    self.model_cfg.max_seq_len - 1)
                oh = np.zeros((1, P), np.float32)
                if ci == n_chunks - 1:
                    oh[0, L - 1 - lo] = 1.0
                feed = {"tokens": jnp.asarray(tokens),
                        "positions": jnp.asarray(positions[None, :]),
                        "chunk_start": jnp.asarray([lo], jnp.int32),
                        "lengths": jnp.asarray([n], jnp.int32),
                        "last_onehot": jnp.asarray(oh),
                        "page_table": jnp.asarray(row[None, :])}
                logits, self._pools = entry(self._params, self._pools,
                                            feed)
            logits = np.asarray(logits)
        telemetry.counter_add("decode.prefills", 1)
        telemetry.counter_add("decode.prefill_tokens", L - k * P)
        # the store adopts every FULL prompt page (strictly before the
        # page receiving decode writes); repoint the table at the
        # canonical pages and keep only the tail pages private
        n_full = L // P
        if n_full > k:
            held, canon = self.prefix_store.insert(
                req.seq, [int(p) for p in row[:n_full]], start_block=k)
            row[k:n_full] = canon
            req.shared_blocks.extend(held)
            req.pages = pages[n_full - k:]
        self._append_token(req, logits[0])
        req.pos_next = L
        if req.finished():
            self._retire(req)
        else:
            self._active.append(req)

    def _ship_prefill(self, req: ShipPrefillRequest):
        """Prefill-tier work (serving/disagg.py): run the prompt's
        prefill, read the finished pages back to host, pack the
        versioned per-page-CRC shipment, free the pages, resolve with
        the bytes. ``disagg.ship`` faults inject here — a failure is a
        per-request error; the pool stays clean."""
        import jax.numpy as jnp

        from . import disagg

        pages: List[int] = []
        try:
            faults.maybe_fail("disagg.ship", tokens=int(req.prompt.size))
            L = int(req.prompt.size)
            n_pages = self.pool.pages_for_tokens(L)
            pages = self.pool.try_alloc(n_pages)
            if not pages:
                raise KVCacheExhaustedError(
                    f"prefill tier cannot seat {n_pages} pages right now")
            bucket = next(b for b in self.config.prefill_buckets
                          if b >= L)
            row = np.zeros(self._mp, np.int32)
            row[:n_pages] = pages
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :L] = req.prompt
            oh = np.zeros((1, bucket), np.float32)
            oh[0, L - 1] = 1.0
            feed = {"tokens": jnp.asarray(tokens),
                    "lengths": jnp.asarray([L], jnp.int32),
                    "last_onehot": jnp.asarray(oh),
                    "page_table": jnp.asarray(row[None, :])}
            entry = self._entry("prefill", bucket)
            with telemetry.timer("decode.prefill_ms"):
                logits, self._pools = entry(self._params, self._pools,
                                            feed)
                logits = np.asarray(logits)
            idx = np.asarray(pages, np.int64)
            layer_pages = {name: np.asarray(self._pools[name])[idx]
                           for name in sorted(self._pools)}
            blob = disagg.pack_shipment(req.prompt, self.config.page_size,
                                        layer_pages, logits[0])
            self.pool.free(pages)
            pages = []
            telemetry.counter_add("disagg.ships", 1)
            telemetry.counter_add("disagg.ship_bytes", len(blob))
            req.resolve(blob)
        except BaseException as e:
            if pages:
                self.pool.free(pages)
            telemetry.counter_add("decode.errors", 1, exc=type(e).__name__)
            req.fail(e if isinstance(e, ServingError) else ServingError(
                f"prefill shipment failed: {e!r}"))

    def _admit_shipped(self, req: GenerationRequest) -> bool:
        """Decode-tier admission (serving/disagg.py): fetch the
        prompt's KV page shipment from a prefill replica, CRC-verify,
        install the pages into the pool arrays and seat the request
        with its first token sampled from the SHIPPED logits. Returns
        False on ANY failure — connection, CRC reject, no pool
        headroom — so the caller falls back to a local prefill
        (``disagg.fallback_prefills``); a corrupted shipment is
        re-prefilled, never served."""
        from . import disagg

        import zlib

        urls = self.config.prefill_urls
        pages: List[int] = []
        try:
            url = urls[zlib.crc32(req.seq.tobytes()) % len(urls)]
            blob = disagg.fetch_prefill(url, req.seq)
            ship = disagg.unpack_shipment(blob)   # raises on CRC reject
            L = int(req.seq.size)
            if (ship["page_size"] != self.config.page_size
                    or ship["tokens"] != [int(t) for t in req.seq]):
                raise disagg.ShipmentError(
                    "shipment does not match the request")
            need = self.pool.pages_for_tokens(L + req.max_new_tokens)
            pages = self.pool.try_alloc(need)
            if not pages:
                return False
            n_ship = ship["n_pages"]
            idx = np.asarray(pages[:n_ship], np.int64)
            for name, arr in ship["layers"].items():
                self._pools[name] = self._pools[name].at[idx].set(arr)
            req.pages = pages
            pages = []
            row = np.zeros(self._mp, np.int32)
            row[:len(req.pages)] = req.pages
            req.table_row = row
            telemetry.counter_add("disagg.installs", 1)
            telemetry.counter_add("decode.prefills", 1)
            self._append_token(req, np.asarray(ship["logits"]))
            req.pos_next = L
            if req.finished():
                self._retire(req)
            else:
                self._active.append(req)
            return True
        except Exception as e:
            if pages:
                self.pool.free(pages)
            telemetry.counter_add("disagg.fallback_prefills", 1,
                                  exc=type(e).__name__)
            return False

    def _run_step(self):
        """DECODE phase: one fixed-shape step over the padded slot
        array; per-request deadlines checked here, at step granularity."""
        import jax.numpy as jnp

        delay_ms = float(_flag("decode_step_delay_ms"))
        if delay_ms > 0:   # chaos/bench pacing knob — off by default
            time.sleep(delay_ms / 1e3)
        now = time.monotonic()
        for req in [r for r in self._active if r.expired(now)]:
            self._active.remove(req)
            telemetry.counter_add("decode.deadline_expired", 1,
                                  phase="generation")
            self._retire(req, error=DeadlineExceededError(
                f"generation deadline elapsed after {len(req.tokens)} of "
                f"{req.max_new_tokens} tokens"))
        if not self._active:
            return
        active = self._active
        bucket = self.config.bucket(len(active))
        faults.maybe_fail("decode.step", active=len(active), bucket=bucket)
        tokens = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        table = np.zeros((bucket, self._mp), np.int32)
        for i, req in enumerate(active):
            tokens[i] = req.last_token
            positions[i] = req.pos_next
            table[i] = req.table_row
        feed = {"tokens": jnp.asarray(tokens),
                "positions": jnp.asarray(positions),
                "page_table": jnp.asarray(table)}
        entry = self._entry("step", bucket)
        with telemetry.timer("decode.step_ms"):
            logits, self._pools = entry(self._params, self._pools, feed)
            logits = np.asarray(logits)
        telemetry.counter_add("decode.steps", 1)
        telemetry.counter_add("decode.tokens", len(active))
        telemetry.observe("decode.batch_occupancy", len(active) / bucket)
        still = []
        for i, req in enumerate(active):
            self._append_token(req, logits[i])
            req.pos_next += 1
            if req.finished():
                self._retire(req)
            else:
                still.append(req)
        self._active = still

    def _journal_tick(self):
        """Replicate session snapshots to the router at step-boundary
        cadence (serving/session.py). Runs on the worker thread right
        after a step — the snapshot is a consistent cut: every accepted
        token is in it, the RNG state has consumed exactly those draws.
        A sink failure (router briefly down) only costs replay depth,
        never the generation (session.journal_errors)."""
        sink = self.journal_sink
        stride = self._journal_stride
        if sink is None or stride <= 0:
            return
        now = time.monotonic()
        records = []
        for req in self._active:
            if req.session_id is None or not req.tokens:
                continue
            if (int(req.prior.size) + len(req.tokens)) % stride == 0:
                records.append(
                    req.journal_record(self.config.page_size, now))
        if not records:
            return
        try:
            sink(records)
        except Exception as e:
            telemetry.counter_add("session.journal_errors", 1,
                                  exc=type(e).__name__)

    def _append_token(self, req: GenerationRequest, logits_row: np.ndarray):
        tok = req.sample(logits_row)
        now = time.monotonic()
        if req.t_first is None:
            req.t_first = now
        req.tokens.append(tok)
        req.token_walls.append(now)
        req.last_token = tok

    def _retire(self, req: GenerationRequest, error: Optional[BaseException]
                = None):
        """Slot recycling: free the request's PRIVATE pages, drop its
        prefix-store references and resolve/fail it — finished
        sequences leave WITHOUT draining the batch. Shared pages stay
        resident in the store (that is the cache)."""
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []
        if req.shared_blocks:
            self.prefix_store.release(req.shared_blocks)
            req.shared_blocks = []
        telemetry.counter_add("decode.retired", 1)
        telemetry.observe("decode.request_ms",
                          (time.monotonic() - req.t_submit) * 1e3,
                          kind="timer")
        if error is not None:
            req.fail(error)
        else:
            req.resolve(np.asarray(req.tokens, np.int32))


def decode_engine_from_dir(model_dir: str,
                           config: Optional[DecodeConfig] = None,
                           version: int = 0) -> DecodeEngine:
    """Servable dir (models/decoder_lm.save_decoder_lm) -> engine — the
    frozen-model path the HTTP server and cluster plane use."""
    from ..models.decoder_lm import load_decoder_lm

    cfg, params = load_decoder_lm(model_dir)
    return DecodeEngine(cfg, params, config=config, version=version)


def demo_engine(config: Optional[DecodeConfig] = None,
                model_cfg: Optional[DecoderLMConfig] = None,
                seed: int = 0) -> DecodeEngine:
    """Deterministically-initialised small LM engine (tests/bench)."""
    cfg = model_cfg or DecoderLMConfig()
    return DecodeEngine(cfg, decoder_lm_params(cfg, seed), config=config)
