"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).

append_regularization_ops adds `grad += coeff * param` (L2) or
`grad += coeff * sign(param)` (L1) ops before the optimizer ops — the same
program-rewrite mechanism as the reference.
"""

from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff


class L2Decay(WeightDecayRegularizer):
    def append(self, block, param, grad):
        scaled = block.create_var(stop_gradient=True, dtype=grad.dtype)
        block.append_op("scale", {"X": [param]}, {"Out": [scaled]},
                        {"scale": self._coeff})
        out = block.create_var(stop_gradient=True, dtype=grad.dtype)
        block.append_op("sum", {"X": [grad, scaled]}, {"Out": [out]}, {})
        return out


class L1Decay(WeightDecayRegularizer):
    def append(self, block, param, grad):
        sign = block.create_var(stop_gradient=True, dtype=grad.dtype)
        block.append_op("sign", {"X": [param]}, {"Out": [sign]}, {})
        scaled = block.create_var(stop_gradient=True, dtype=grad.dtype)
        block.append_op("scale", {"X": [sign]}, {"Out": [scaled]},
                        {"scale": self._coeff})
        out = block.create_var(stop_gradient=True, dtype=grad.dtype)
        block.append_op("sum", {"X": [grad, scaled]}, {"Out": [out]}, {})
        return out


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay


def append_regularization_ops(params_grads, global_regularizer=None):
    out = []
    for p, g in params_grads:
        reg = getattr(p, "regularizer", None) or global_regularizer
        if reg is None or g is None:
            out.append((p, g))
            continue
        # current block, not p.block: under GradientMergeOptimizer the update
        # lives in a conditional sub-block and regularization must join it
        block = p.block.program.current_block()
        new_g = reg.append(block, p, g)
        out.append((p, new_g))
    return out
