"""Data pipeline: reader decorators + Dataset/Sampler/DataLoader.

Capability mirror of the reference's three data stacks re-designed for TPU:

* reader decorators (python/paddle/reader/decorator.py — batch, shuffle,
  buffered, cache, chain, compose, map_readers, xmap_readers): pure-Python
  generator combinators, kept 1:1.
* `DataLoader.from_generator` (python/paddle/fluid/reader.py:147): the
  reference pushes LoDTensors through a C++ BlockingQueue into
  `create_py_reader` ops; here a background thread prefetches ready
  batches into a bounded queue and (optionally) `jax.device_put`s them so
  host→device copy overlaps the previous step (the buffered_reader.cc
  double-buffering role).
* `DataLoader(dataset, ...)` map-style path (fluid/reader.py DataLoader +
  dataloader/dataloader_iter.py): Dataset/BatchSampler/collate with a
  thread pool standing in for the mmap-shared-memory worker processes
  (batches are numpy; XLA owns the device transfer — no per-worker device
  context to isolate, so threads suffice on the host side).
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

__all__ = [
    # decorators
    "batch", "shuffle", "buffered", "cache", "chain", "compose", "firstn",
    "map_readers", "xmap_readers", "ComposeNotAligned",
    # datasets / samplers / loader
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
    "DataLoader", "default_collate_fn",
]


# ---------------------------------------------------------------------------
# reader decorators (reference: python/paddle/reader/decorator.py)
# ---------------------------------------------------------------------------

def batch(reader: Callable, batch_size: int, drop_last: bool = False):
    """Group samples into lists of `batch_size`."""

    def batched():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batched


def shuffle(reader: Callable, buf_size: int, seed: Optional[int] = None):
    """Pool-based shuffle with a `buf_size` reservoir."""

    def shuffled():
        rng = _random.Random(seed)
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return shuffled


def buffered(reader: Callable, size: int):
    """Background-thread prefetch of up to `size` samples."""

    _end = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        err: List[BaseException] = []

        def produce():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                q.put(_end)

        t = threading.Thread(target=produce, name="pt-reader-buffer",
                             daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _end:
                break
            yield item
        if err:
            raise err[0]

    return buffered_reader


def cache(reader: Callable):
    all_data: List[Any] = []
    filled = [False]

    def cached():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        yield from all_data

    return cached


def chain(*readers):
    def chained():
        for r in readers:
            yield from r()

    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment: bool = True):
    """Zip readers into flat tuples of their samples. With
    check_alignment=True (default), raises ComposeNotAligned if readers have
    different lengths (reference: reader/decorator.py compose); with False,
    stops at the longest reader, padding missing slots with None."""

    _missing = object()

    def composed():
        iters = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*iters, fillvalue=_missing):
                if any(i is _missing for i in items):
                    raise ComposeNotAligned(
                        "compose: input readers yielded different lengths")
                yield tuple(x for i in items
                            for x in (i if isinstance(i, tuple) else (i,)))
        else:
            for items in zip(*iters):  # stop at the shortest (reference)
                yield tuple(x for i in items
                            for x in (i if isinstance(i, tuple) else (i,)))

    return composed


def firstn(reader: Callable, n: int):
    def firstn_reader():
        yield from itertools.islice(reader(), n)

    return firstn_reader


def map_readers(func: Callable, *readers):
    def mapped():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return mapped


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False):
    """Parallel map over a reader with `process_num` worker threads."""

    _end = object()

    def xmapped():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        errors: List[BaseException] = []

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:
                errors.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(_end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _end:
                        return
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:
                errors.append(e)
            finally:
                # always post the sentinel so the consumer can't deadlock on
                # a failed worker
                out_q.put(_end)

        threading.Thread(target=feed, name="pt-reader-xmap-feed",
                         daemon=True).start()
        for i in range(process_num):
            threading.Thread(target=work, name=f"pt-reader-xmap-{i}",
                             daemon=True).start()
        done = 0
        pending = {}
        next_idx = 0
        while done < process_num:
            item = out_q.get()
            if item is _end:
                done += 1
                continue
            i, mapped = item
            if not order:
                yield mapped
            else:
                pending[i] = mapped
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if errors:
            raise errors[0]
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xmapped


# ---------------------------------------------------------------------------
# Dataset / Sampler (reference: python/paddle/fluid/dataloader/)
# ---------------------------------------------------------------------------

class Dataset:
    """Map-style dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[np.ndarray]):
        self.tensors = [np.asarray(t) for t in tensors]
        n = len(self.tensors[0])
        if any(len(t) != n for t in self.tensors):
            raise ValueError("all tensors must share dim 0")

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            s = ds[idx]
            out.extend(s if isinstance(s, tuple) else (s,))
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement: bool = False,
                 num_samples: Optional[int] = None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = self.generator or np.random
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        idx = np.arange(n)
        rng.shuffle(idx)
        return iter(idx[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Yields lists of indices (reference: dataloader/batch_sampler.py)."""

    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle: bool = False, batch_size: int = 1,
                 drop_last: bool = False):
        super().__init__(dataset)
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        b = []
        for idx in self.sampler:
            b.append(idx)
            if len(b) == self.batch_size:
                yield b
                b = []
        if b and not self.drop_last:
            yield b

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch_list):
    """List of samples → stacked numpy arrays (field-wise)."""
    first = batch_list[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate_fn([s[i] for s in batch_list])
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([s[k] for s in batch_list])
                for k in first}
    return np.stack([np.asarray(s) for s in batch_list])


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

_END = object()


def _prefetch_device_put(batch, mesh=None):
    """device_put a prefetched batch with the active mesh's NamedSharding.

    The double-buffer thread used to target the default device; under a
    mesh the first pjit touch then re-laid the buffer out across devices
    (an extra device-to-device copy on the critical path). Sharding the
    batch dim over 'dp' here — exactly the compiled executor's default
    feed sharding — makes the H2D copy land in final layout while the
    previous step computes, so the jitted step sees ready buffers.
    Arrays whose batch dim doesn't divide dp (ragged tails) replicate,
    matching the executor's dp-divisibility fallback.
    """
    import jax

    if mesh is None:
        from .parallel.mesh import get_mesh

        mesh = get_mesh()
    if mesh is None:
        return jax.tree.map(jax.device_put, batch)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .core import telemetry

    dp = mesh.shape.get("dp")

    def put(x):
        spec = ()
        if dp and getattr(x, "ndim", len(np.shape(x))) >= 1 \
                and np.shape(x)[0] % dp == 0:
            spec = ("dp",)
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    telemetry.counter_add("reader.sharded_device_puts", 1)
    return jax.tree.map(put, batch)


class _GeneratorLoader:
    """from_generator loader: queue-fed, iterable (reference:
    fluid/reader.py GeneratorLoader). The prefetch thread device_puts
    with the active mesh's sharding (see _prefetch_device_put).

    Resumable: ``state_dict()`` returns the stream cursor (batches the
    current iteration has delivered, skipped ones included) and
    ``set_state()`` arms the NEXT iteration to fast-forward past that
    many batches — the exact-resume hook the crash-consistent checkpoint
    stack (paddle_tpu/checkpoint.py, ElasticRunner) stores and restores.
    Exactness requires the underlying generator to be deterministic.

    Elastic worlds: ``set_world(world_size, trainer_id)`` turns the
    loader into one member of a round-robin partition of the SAME
    deterministic global stream — trainer t of W delivers exactly the
    batches whose global index ≡ t (mod W). The cursor is the GLOBAL
    stream position, so a checkpoint saved at one world size restores
    into any other: every new trainer arms the same global cursor and
    takes its own residue class — the reader re-split of a world-size-
    changing resume needs no data munging (reader.cursor_resplits
    counts the world-changing restores)."""

    def __init__(self, feed_list=None, capacity: int = 16,
                 return_list: bool = False, use_device_put: bool = True,
                 mesh=None):
        self.feed_list = feed_list or []
        self.capacity = capacity
        self.return_list = return_list
        self.use_device_put = use_device_put
        self.mesh = mesh
        self._gen: Optional[Callable] = None
        self._places = None
        self._position = 0        # GLOBAL cursor of the live iteration
        self._skip_next = 0       # armed by set_state for the next iteration
        self._world_size = 1
        self._trainer_id = 0

    # -- resumable cursor --------------------------------------------------
    def set_world(self, world_size: int, trainer_id: int):
        """Partition the global stream round-robin: this loader delivers
        batches whose global index ≡ trainer_id (mod world_size)."""
        world_size = int(world_size)
        trainer_id = int(trainer_id)
        if world_size < 1 or not 0 <= trainer_id < world_size:
            raise ValueError(
                f"set_world: need 0 <= trainer_id < world_size, got "
                f"trainer {trainer_id} of {world_size}")
        self._world_size = world_size
        self._trainer_id = trainer_id
        return self

    def state_dict(self) -> Dict[str, int]:
        """{'batches': N} — GLOBAL position in the (deterministic) batch
        stream (plus the world shape when one is configured)."""
        state = {"batches": int(self._position)}
        if self._world_size > 1:
            state["world_size"] = self._world_size
            state["trainer_id"] = self._trainer_id
        return state

    def set_state(self, state: Dict[str, int]):
        """Arm the next iteration to discard the first N GLOBAL batches,
        so the first delivered batch is the one a restored run expects.
        The cursor is global: a state saved by any member of any world
        size restores into this loader's (possibly different) world —
        the re-split is just this loader's own residue class applied
        past the same cursor."""
        self._skip_next = max(0, int(state.get("batches", 0)))
        self._position = self._skip_next
        saved_world = int(state.get("world_size", 1))
        if saved_world != self._world_size:
            from .core import telemetry as _telemetry
            _telemetry.counter_add(
                "reader.cursor_resplits", 1, saved_world=saved_world,
                world=self._world_size, trainer=self._trainer_id)

    # -- configuration ----------------------------------------------------
    def set_sample_generator(self, generator, batch_size: int,
                             drop_last: bool = True, places=None):
        self.set_sample_list_generator(
            batch(lambda: generator(), batch_size, drop_last), places)
        return self

    def set_sample_list_generator(self, generator, places=None):
        def to_batches():
            for sample_list in generator():
                yield default_collate_fn(sample_list)

        self.set_batch_generator(to_batches, places)
        return self

    def set_batch_generator(self, generator, places=None):
        self._gen = generator
        self._places = places
        return self

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        if self._gen is None:
            raise RuntimeError(
                "DataLoader not configured — call set_sample_generator / "
                "set_sample_list_generator / set_batch_generator first")
        names = [getattr(v, "name", str(v)) for v in self.feed_list]
        q: queue.Queue = queue.Queue(maxsize=self.capacity)
        err: List[BaseException] = []

        def produce():
            try:
                for b in self._gen():
                    if self.use_device_put:
                        b = _prefetch_device_put(b, self.mesh)
                    q.put(b)
            except BaseException as e:
                err.append(e)
            finally:
                q.put(_END)

        threading.Thread(target=produce, name="pt-reader-prefetch",
                         daemon=True).start()
        skip, self._skip_next = self._skip_next, 0
        self._position = 0
        import time as _time

        from .core import telemetry as _telemetry
        while True:
            # consumer-side queue wait: the training loop blocked on the
            # prefetch thread — the goodput ledger's data_wait phase
            t_wait = _time.perf_counter()
            item = q.get()
            _telemetry.observe("reader.data_wait_ms",
                               (_time.perf_counter() - t_wait) * 1e3,
                               kind="timer")
            if item is _END:
                break
            index = self._position           # global index of this batch
            self._position += 1
            if skip > 0:
                # fast-forward to the restored cursor: the batch was
                # produced (deterministic stream) but never delivered
                skip -= 1
                continue
            if index % self._world_size != self._trainer_id:
                # another trainer's residue class — consumed from the
                # global stream (the cursor advances) but not delivered
                continue
            if self.return_list or not names:
                yield list(item) if isinstance(item, tuple) else [item]
            else:
                arrays = item if isinstance(item, (tuple, list)) else (item,)
                yield dict(zip(names, arrays))
        if err:
            raise err[0]


class DataLoader:
    """Two construction modes, mirroring the reference:

    * ``DataLoader.from_generator(feed_list, capacity)`` then
      ``set_*_generator`` — iterable loader yielding feed dicts.
    * ``DataLoader(dataset, batch_size=.., shuffle=..)`` — map-style with
      sampler + collate + threaded workers.
    """

    def __init__(self, dataset: Optional[Dataset] = None, feed_list=None,
                 places=None, return_list: bool = True,
                 batch_sampler: Optional[BatchSampler] = None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable] = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 use_shared_memory: bool = True,
                 prefetch_factor: int = 2, timeout: float = 0,
                 worker_init_fn=None):
        self.dataset = dataset
        self.feed_list = feed_list or []
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        # True -> PROCESS workers + shared-memory result transport
        # (reference: reader.py:147 multiprocess DataLoader with
        # memory/allocation/mmap_allocator); False -> thread pool
        self.use_shared_memory = bool(use_shared_memory)
        self.prefetch_factor = prefetch_factor
        self._iterable_dataset = isinstance(dataset, IterableDataset)
        if self._iterable_dataset:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                                  batch_size=batch_size,
                                                  drop_last=drop_last)

    @staticmethod
    def from_generator(feed_list=None, capacity: int = 16, iterable: bool = True,
                       return_list: bool = False, use_double_buffer: bool = True,
                       use_multiprocess: bool = False,
                       drop_last: bool = True) -> _GeneratorLoader:
        # use_double_buffer → device_put in the prefetch thread so the H2D
        # copy overlaps the previous step (buffered_reader.cc role)
        return _GeneratorLoader(feed_list, capacity, return_list,
                                use_device_put=use_double_buffer)

    def __len__(self):
        if self._iterable_dataset:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _emit(self, collated):
        if self.return_list or not self.feed_list:
            return list(collated) if isinstance(collated, tuple) else [collated]
        names = [getattr(v, "name", str(v)) for v in self.feed_list]
        arrays = collated if isinstance(collated, (tuple, list)) else (collated,)
        return dict(zip(names, arrays))

    def _iter_process_workers(self):
        """Fork-based worker processes with shared-memory batch transport
        (reference: dataloader/dataloader_iter.py _DataLoaderIterMultiProcess
        + memory/allocation/mmap_allocator.cc): each worker pulls index
        lists from a task queue, collates, copies every array of the
        batch into a multiprocessing.shared_memory block and ships only
        (name, dtype, shape) descriptors — Python-heavy preprocessing
        scales past the GIL, and large batches cross processes without
        being pickled through a pipe. In-order delivery via batch ids."""
        import multiprocessing as mp
        from multiprocessing import shared_memory

        ctx = mp.get_context("fork")
        # bounded task queue = backpressure: at most
        # num_workers * prefetch_factor batches in flight, so /dev/shm
        # holds a bounded working set, not the whole epoch
        depth = max(1, self.num_workers * int(self.prefetch_factor))
        task_q = ctx.Queue(maxsize=depth)
        result_q = ctx.Queue()
        batches = list(self.batch_sampler)
        nw = self.num_workers

        dataset, collate = self.dataset, self.collate_fn

        def worker():
            while True:
                job = task_q.get()
                if job is None:
                    return
                bid, indices = job
                try:
                    collated = collate([dataset[i] for i in indices])
                    arrays = collated if isinstance(collated, (tuple, list)) \
                        else (collated,)
                    descs = []
                    for a in arrays:
                        a = np.ascontiguousarray(a)
                        shm = shared_memory.SharedMemory(
                            create=True, size=max(a.nbytes, 1))
                        np.ndarray(a.shape, a.dtype,
                                   buffer=shm.buf)[...] = a
                        descs.append((shm.name, str(a.dtype), a.shape))
                        shm.close()
                    result_q.put((bid, descs, None))
                except Exception as e:        # surface, don't hang
                    result_q.put((bid, None, repr(e)))

        procs = [ctx.Process(target=worker, daemon=True)
                 for _ in range(nw)]
        for p in procs:
            p.start()

        def feed():
            for bid, indices in enumerate(batches):
                task_q.put((bid, indices))      # blocks at depth
            for _ in range(nw):
                task_q.put(None)

        feeder = threading.Thread(target=feed, name="pt-reader-shmem-feed",
                                  daemon=True)
        feeder.start()
        pending: Dict[int, Any] = {}

        def unlink_descs(descs):
            for name, _, _ in descs or ():
                try:
                    shm = shared_memory.SharedMemory(name=name)
                    shm.close()
                    shm.unlink()
                except FileNotFoundError:
                    pass

        try:
            next_bid = 0
            received = 0
            while received < len(batches):
                # bounded waits + worker-liveness check: a worker killed
                # without posting a result (OOM kill, segfault in user
                # dataset code) must raise, not hang the training loop.
                # A worker killed while IDLE leaves its queued tasks for
                # the survivors, so a crash alone is not fatal — raise
                # only once results also stop flowing (progress stall).
                import time as _time

                last_progress = _time.monotonic()
                while True:
                    try:
                        bid, descs, err = result_q.get(timeout=5.0)
                        break
                    except queue.Empty:
                        crashed = [p.exitcode for p in procs
                                   if not p.is_alive()
                                   and p.exitcode not in (0, None)]
                        stalled = _time.monotonic() - last_progress > 60.0
                        if crashed and (stalled or
                                        all(not p.is_alive()
                                            for p in procs)):
                            raise RuntimeError(
                                f"DataLoader worker died (exitcodes "
                                f"{crashed}) and results stalled with "
                                f"{len(batches) - received} batches "
                                f"outstanding — a batch was likely lost "
                                f"with the worker")
                        if all(not p.is_alive() for p in procs):
                            raise RuntimeError(
                                "all DataLoader workers exited with "
                                f"{len(batches) - received} batches "
                                "outstanding")
                received += 1
                if err is not None:
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {bid}: {err}")
                pending[bid] = descs
                while next_bid in pending:
                    arrays = []
                    for name, dtype, shape in pending.pop(next_bid):
                        shm = shared_memory.SharedMemory(name=name)
                        arrays.append(np.array(np.ndarray(
                            shape, dtype, buffer=shm.buf)))
                        shm.close()
                        shm.unlink()
                    collated = tuple(arrays) if len(arrays) != 1 \
                        else arrays[0]
                    yield self._emit(collated)
                    next_bid += 1
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)
            # reclaim shm of batches never consumed (error / early close)
            for descs in pending.values():
                unlink_descs(descs)
            try:
                while True:
                    _, descs, _ = result_q.get_nowait()
                    unlink_descs(descs)
            except queue.Empty:
                pass

    def __iter__(self):
        if self._iterable_dataset:
            def gen():
                b = []
                for sample in self.dataset:
                    b.append(sample)
                    if len(b) == self.batch_size:
                        yield self.collate_fn(b)
                        b = []
                if b and not self.drop_last:
                    yield self.collate_fn(b)

            for collated in gen():
                yield self._emit(collated)
            return

        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield self._emit(self._fetch(indices))
            return

        if self.use_shared_memory:
            yield from self._iter_process_workers()
            return

        # threaded workers with in-order delivery
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(self.num_workers) as pool:
            batches = list(self.batch_sampler)
            depth = self.num_workers * self.prefetch_factor
            futures: "queue.Queue" = queue.Queue()
            it = iter(batches)
            submitted = 0
            for indices in itertools.islice(it, depth):
                futures.put(pool.submit(self._fetch, indices))
                submitted += 1
            while submitted > 0:
                f = futures.get()
                submitted -= 1
                nxt = next(it, None)
                if nxt is not None:
                    futures.put(pool.submit(self._fetch, nxt))
                    submitted += 1
                yield self._emit(f.result())
