"""Sharded / async checkpointing over orbax.

Capability mirror of the reference checkpoint stack (SURVEY.md §5:
io.save_persistables / load_persistables emit save/load ops,
framework/save_load_util.cc fast path, checkpoint_notify for PS snapshots,
hapi ModelCheckpoint) re-designed for TPU scale: persistables are a pytree
of (possibly sharded) jax.Arrays; orbax writes each shard from its home
device (no host gather) and can do so ASYNCHRONOUSLY so training continues
while the previous step's state flushes — the PS-era "snapshot without
stopping trainers" capability, single-program style.

The io.py save/load (per-var .npy / .npz) surface remains for small models
and inference export; this module is the training-time path.

CheckpointManager adds retention + auto-resume: the checkpoint-restart
failure-recovery story (the reference's collective mode has none —
SURVEY.md §5 failure detection)."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from .core.ir import Program, default_main_program
from .core.scope import Scope, global_scope


def _persistable_state(program: Program, scope: Scope) -> Dict[str, Any]:
    state = {}
    for var in program.global_block().vars.values():
        if getattr(var, "persistable", False):
            v = scope.find_var(var.name)
            if v is not None:
                state[var.name] = v
    step = scope.find_var("@STEP_COUNTER@")
    if step is not None:
        state["@STEP_COUNTER@"] = np.asarray(step)
    return state


_async_checkpointer = None


def save_checkpoint(path: str, program: Optional[Program] = None,
                    scope: Optional[Scope] = None, async_save: bool = False):
    """Write all persistables (sharded arrays stay sharded on disk).

    async_save=True returns immediately; the write completes in the
    background (call wait_for_checkpoint() to join)."""
    global _async_checkpointer
    import orbax.checkpoint as ocp

    program = program or default_main_program()
    scope = scope or global_scope()
    state = _persistable_state(program, scope)
    if not state:
        raise ValueError("no persistable state in scope — run the startup "
                         "program first")
    path = os.path.abspath(path)
    if async_save:
        if _async_checkpointer is None:
            _async_checkpointer = ocp.AsyncCheckpointer(
                ocp.PyTreeCheckpointHandler())
        _async_checkpointer.save(path, state, force=True)
    else:
        # the PyTree handler under the sync Checkpointer commits before
        # returning (StandardCheckpointer finalises on a background
        # thread — a restore right after save can miss the directory)
        with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
            ckptr.save(path, state, force=True)
    return path


def wait_for_checkpoint():
    """Join any in-flight async save."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()


def load_checkpoint(path: str, program: Optional[Program] = None,
                    scope: Optional[Scope] = None) -> int:
    """Restore persistables into the scope. Returns the restored step."""
    import orbax.checkpoint as ocp

    program = program or default_main_program()
    scope = scope or global_scope()
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        state = ckptr.restore(os.path.abspath(path))
    step = 0
    for name, val in state.items():
        if name == "@STEP_COUNTER@":
            step = int(np.asarray(val))
        scope.set(name, val)
    return step


class CheckpointManager:
    """Retention + auto-resume driver (reference: hapi callbacks
    ModelCheckpoint + the PS checkpoint_notify flow; orbax
    CheckpointManager underneath).

    mgr = CheckpointManager(dir, max_to_keep=3)
    start = mgr.restore_latest(program, scope)      # 0 if fresh
    for step in range(start, N):
        ...train...
        mgr.save(step, program, scope)              # honors save_interval
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=opts)

    def save(self, step: int, program: Optional[Program] = None,
             scope: Optional[Scope] = None) -> bool:
        import orbax.checkpoint as ocp

        state = _persistable_state(program or default_main_program(),
                                   scope or global_scope())
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore_latest(self, program: Optional[Program] = None,
                       scope: Optional[Scope] = None) -> int:
        """Load the newest checkpoint if any; returns its step (0 if none).
        This is the failure-recovery entry point: rerun the same script and
        training resumes."""
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return 0
        program = program or default_main_program()
        scope = scope or global_scope()
        target = _persistable_state(program, scope)
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target if target else None))
        for name, val in state.items():
            scope.set(name, val)
        return int(step)

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
