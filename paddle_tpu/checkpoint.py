"""Crash-consistent checkpointing: atomic commits, integrity verification,
exact-resume training snapshots.

Capability mirror of the reference checkpoint stack (SURVEY.md §5:
io.save_persistables / load_persistables emit save/load ops,
framework/save_load_util.cc fast path, checkpoint_notify for PS snapshots,
hapi ModelCheckpoint) hardened to the CheckFreq / Check-N-Run bar: a
checkpoint either exists COMPLETELY or not at all, restore never trusts
bytes it has not verified, and a resumed run is the run that crashed.

The commit protocol (write_checkpoint_dir):

1. the full state is staged into a ``.tmp-ckpt-*`` sibling directory —
   ``state.npz`` (every array, filesystem-safe encoded names) is written,
   flushed and fsynced;
2. a ``MANIFEST.json`` COMMIT record is written last inside the staging
   dir: per-array CRC32/shape/dtype/nbytes, the whole-file sha256 of
   ``state.npz``, the training step, a monotonic save sequence number,
   and JSON ``extras`` (global RNG state is captured automatically;
   callers add reader cursors, epoch counters, PS step tables);
3. the staging dir is fsynced and atomically ``rename``d to its final
   ``ckpt-<step>`` name; the parent dir is fsynced.

A process killed at ANY point leaves either the previous checkpoints
untouched plus an ignorable uncommitted ``.tmp-ckpt-*`` dir, or the new
checkpoint fully committed — never a torn directory under a final name.

Restore (read_checkpoint_dir / CheckpointManager.restore_latest) verifies
the manifest before a single byte enters the scope: commit marker, file
size, sha256, per-array CRC32/shape/dtype (digest work gated by
``FLAGS_ckpt_verify``). Corrupt or uncommitted checkpoints are moved to a
``.quarantine/`` subdir (``ckpt.verify_failures`` / ``ckpt.quarantined``
telemetry) and ``restore_latest`` falls back to the newest checkpoint
that DOES verify (``ckpt.fallbacks``).

Fault sites for the core/faults.py harness: ``ckpt.save.write`` (before
any byte is staged), ``ckpt.save.commit`` (data durable, manifest/rename
pending), ``ckpt.restore.read`` (per restore candidate). The
``PT_CKPT_CRASH_AT=<site>[@<step>]`` env hook SIGKILLs the process at the
matching site — the kill-during-save subprocess tests drive it.

Async saves go through a single module-level background writer that
commits in submit order; ``wait_for_checkpoint()`` joins it and an atexit
hook joins it on interpreter exit, so process teardown cannot truncate an
in-flight save. The arrays handed to an async save are snapshotted to
host memory at submit time (XLA buffer donation may invalidate device
buffers before the writer runs).

The io.py save/load (per-var .npy / .npz) surface remains for small
models and inference export; this module is the training-time path.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import queue
import shutil
import signal
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .core import faults, telemetry
from .core import flags as _flags
from .core.analysis import lockdep
from .core.ir import Program, default_main_program
from .core.scope import Scope, global_scope
from .io import _decode_name, _encode_name, _fsync_dir

FORMAT = "paddle_tpu-ckpt-v1"
MANIFEST_NAME = "MANIFEST.json"
DATA_NAME = "state.npz"
QUARANTINE_DIRNAME = ".quarantine"
_CKPT_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-ckpt-"


class CheckpointError(RuntimeError):
    """Base for checkpoint protocol failures."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint failed integrity verification (torn write, bit rot,
    uncommitted staging dir, manifest mismatch)."""


# ---------------------------------------------------------------------------
# protocol primitives
# ---------------------------------------------------------------------------

def _maybe_crash(site: str, step) -> None:
    """Kill-during-save test hook: PT_CKPT_CRASH_AT='<site>[@<step>]'
    SIGKILLs the process when the matching fault site is reached — the
    honest version of a machine dying mid-save."""
    spec = os.environ.get("PT_CKPT_CRASH_AT", "")
    if not spec:
        return
    want, _, at = spec.partition("@")
    if want != site:
        return
    if at and step is not None and int(at) != int(step):
        return
    os.kill(os.getpid(), signal.SIGKILL)


def _to_host(v) -> np.ndarray:
    """Own-memory host copy (donated device buffers may be invalidated
    by the time an async writer runs)."""
    import jax

    if hasattr(v, "addressable_shards"):
        v = jax.device_get(v)
    return np.array(v)


def _crc32(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _sharding_manifest_extras(program) -> Optional[Dict[str, Any]]:
    """Sharding configuration of the saving run (rule-table fingerprint +
    ZeRO stage) — recorded so a restore under a DIFFERENT table is
    detected (and counted) as a reshard-on-load. Arrays are always saved
    at GLOBAL shape (_to_host device_gets sharded arrays), so resharding
    is just the next compile's in_shardings — no data munging."""
    from .parallel import axis_rules

    fp = axis_rules.fingerprint()
    zs = getattr(program, "_zero_stage", None) if program is not None else None
    if fp is None and zs is None:
        return None
    out = {"axis_rules": fp, "zero_stage": zs}
    zd = getattr(program, "_zero_degree", None) if program is not None else None
    if zd is not None:
        # the dp degree the ZeRO shards were padded for — a restore into
        # a different degree regroups the state (parallel/zero_regroup)
        out["zero_degree"] = int(zd)
    return out


def _note_resharding(extras: Optional[Dict[str, Any]]):
    """Compare the snapshot's recorded rule table with the active one;
    count a sharding.resharding_events when they differ (the restored
    global arrays re-lay out lazily at the next dispatch)."""
    sh = (extras or {}).get("sharding") or {}
    saved = sh.get("axis_rules")
    if saved is None:
        return
    from .parallel import axis_rules

    active = axis_rules.fingerprint()
    if active != saved:
        telemetry.counter_add("sharding.resharding_events", 1,
                              saved_rules=saved, active_rules=active)


def _rng_state_jsonable() -> list:
    from .generator import get_rng_state

    gen, main, startup = get_rng_state()
    return [list(gen), list(main), list(startup)]


def _restore_rng(extras: Optional[Dict[str, Any]]):
    rng = (extras or {}).get("rng")
    if rng:
        from .generator import set_rng_state

        set_rng_state(rng)


def write_checkpoint_dir(final_dir: str, arrays: Dict[str, Any],
                         extras: Optional[Dict[str, Any]] = None,
                         step: int = 0, seq: int = 0) -> str:
    """Atomically commit `arrays` (+ JSON `extras`) as a verified
    checkpoint directory. See the module docstring for the protocol."""
    t0 = time.perf_counter()
    final_dir = os.path.abspath(final_dir)
    parent = os.path.dirname(final_dir)
    os.makedirs(parent, exist_ok=True)
    faults.maybe_fail("ckpt.save.write", step=int(step))
    _maybe_crash("ckpt.save.write", step)
    host = {name: _to_host(v) for name, v in arrays.items()}
    extras = dict(extras or {})
    extras.setdefault("rng", _rng_state_jsonable())
    tmp = os.path.join(parent, f"{_TMP_PREFIX}{os.path.basename(final_dir)}"
                               f"-{os.getpid()}-{threading.get_ident()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        data_path = os.path.join(tmp, DATA_NAME)
        with open(data_path, "wb") as f:
            np.savez(f, **{_encode_name(k): a for k, a in host.items()})
            f.flush()
            os.fsync(f.fileno())
        manifest = {
            "format": FORMAT,
            "step": int(step),
            "seq": int(seq),
            "ts": time.time(),
            "data_file": DATA_NAME,
            "data_nbytes": os.path.getsize(data_path),
            "data_sha256": _sha256_file(data_path),
            "arrays": {k: {"shape": list(a.shape), "dtype": str(a.dtype),
                           "crc32": _crc32(a), "nbytes": int(a.nbytes)}
                       for k, a in host.items()},
            "extras": extras,
            "committed": True,
        }
        faults.maybe_fail("ckpt.save.commit", step=int(step))
        _maybe_crash("ckpt.save.commit", step)
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        if os.path.exists(final_dir):
            shutil.rmtree(final_dir)    # re-commit of the same step
        os.rename(tmp, final_dir)
        _fsync_dir(parent)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    telemetry.counter_add("ckpt.saves", 1, step=int(step))
    telemetry.counter_add("ckpt.bytes",
                          int(sum(a.nbytes for a in host.values())))
    telemetry.observe("ckpt.save_ms", (time.perf_counter() - t0) * 1e3,
                      kind="timer", step=int(step))
    return final_dir


def verify_checkpoint_dir(path: str,
                          deep: Optional[bool] = None) -> Dict[str, Any]:
    """Check the COMMIT manifest (and, with deep verification, the data
    file's size + sha256) WITHOUT loading arrays. Raises
    CheckpointCorruptError; returns the parsed manifest."""
    if deep is None:
        deep = bool(_flags.flag("ckpt_verify"))
    if not os.path.isdir(path):
        raise CheckpointCorruptError(f"{path}: not a checkpoint directory")
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise CheckpointCorruptError(
            f"{path}: no {MANIFEST_NAME} — save never committed")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: {e}")
    if manifest.get("format") != FORMAT:
        raise CheckpointCorruptError(
            f"{path}: unknown checkpoint format {manifest.get('format')!r}")
    if not manifest.get("committed"):
        raise CheckpointCorruptError(f"{path}: manifest lacks commit marker")
    data = os.path.join(path, manifest.get("data_file", DATA_NAME))
    if not os.path.exists(data):
        raise CheckpointCorruptError(f"{path}: data file missing")
    if deep:
        nbytes = os.path.getsize(data)
        if nbytes != int(manifest.get("data_nbytes", -1)):
            raise CheckpointCorruptError(
                f"{path}: torn data file ({nbytes} bytes, manifest says "
                f"{manifest.get('data_nbytes')})")
        digest = _sha256_file(data)
        if digest != manifest.get("data_sha256"):
            raise CheckpointCorruptError(
                f"{path}: data sha256 mismatch (corrupt bytes)")
    return manifest


def read_checkpoint_dir(path: str) -> Tuple[Dict[str, np.ndarray],
                                            Dict[str, Any]]:
    """Verify, then load: returns ({name: array}, manifest). Every array
    is checked against the manifest's shape/dtype/CRC32 (digest work
    gated by FLAGS_ckpt_verify)."""
    t0 = time.perf_counter()
    path = os.path.abspath(path)
    faults.maybe_fail("ckpt.restore.read", ckpt=os.path.basename(path))
    deep = bool(_flags.flag("ckpt_verify"))
    manifest = verify_checkpoint_dir(path, deep=deep)
    data = os.path.join(path, manifest.get("data_file", DATA_NAME))
    try:
        with np.load(data, allow_pickle=False) as z:
            arrays = {_decode_name(k): z[k] for k in z.files}
    except Exception as e:
        raise CheckpointCorruptError(f"{path}: unreadable data file: {e}")
    want = manifest.get("arrays", {})
    if set(want) != set(arrays):
        raise CheckpointCorruptError(
            f"{path}: array set mismatch — manifest has {len(want)} "
            f"entries, data file has {len(arrays)}")
    if deep:
        for name, spec in want.items():
            a = arrays[name]
            if list(a.shape) != list(spec["shape"]) or \
                    str(a.dtype) != spec["dtype"]:
                raise CheckpointCorruptError(
                    f"{path}: '{name}' is {a.dtype}{list(a.shape)}, "
                    f"manifest says {spec['dtype']}{spec['shape']}")
            if _crc32(a) != int(spec["crc32"]):
                raise CheckpointCorruptError(
                    f"{path}: CRC32 mismatch for '{name}'")
    telemetry.counter_add("ckpt.restores", 1)
    telemetry.observe("ckpt.restore_ms", (time.perf_counter() - t0) * 1e3,
                      kind="timer")
    return arrays, manifest


def quarantine_checkpoint(path: str, reason: str) -> Optional[str]:
    """Move a rejected checkpoint/staging dir aside (never delete — the
    operator may want the forensics) and account for it."""
    parent = os.path.dirname(os.path.abspath(path))
    qdir = os.path.join(parent, QUARANTINE_DIRNAME)
    dest = os.path.join(
        qdir, f"{os.path.basename(path)}.{int(time.time() * 1e3)}")
    try:
        os.makedirs(qdir, exist_ok=True)
        os.rename(path, dest)
    except OSError:
        shutil.rmtree(path, ignore_errors=True)
        dest = None
    telemetry.counter_add("ckpt.quarantined", 1, reason=reason)
    return dest


# ---------------------------------------------------------------------------
# async writer (the satellite: exit can't truncate an in-flight save)
# ---------------------------------------------------------------------------

class AsyncCheckpointer:
    """Single background writer: async saves commit in submit order. A
    failed job's exception re-raises on the next submit/wait (the save
    API stays fire-and-forget, but failures are never silent)."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = lockdep.lock("ckpt.async_writer")
        self._failure: Optional[BaseException] = None

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="pt-ckpt-async-writer",
                    daemon=True)
                self._thread.start()

    def _loop(self):
        while True:
            fn = self._q.get()
            try:
                fn()
            except BaseException as e:   # surfaced on next submit/wait
                with self._lock:
                    self._failure = e
            finally:
                self._q.task_done()

    def _raise_failure(self):
        with self._lock:
            e, self._failure = self._failure, None
        if e is not None:
            raise e

    def submit(self, fn):
        self._raise_failure()
        self._ensure_thread()
        self._q.put(fn)

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Join the queue; with ``timeout`` the join is BOUNDED — a
        drain path (elastic shutdown, orchestrator SIGTERM) must never
        hang forever behind a wedged writer. Returns True when the
        queue fully drained, False on timeout (pending saves are left
        in flight; the atexit join still gets a chance at them).
        Re-raises a surfaced writer failure either way."""
        if self._thread is not None:
            if timeout is None:
                self._q.join()
            else:
                deadline = time.monotonic() + max(0.0, float(timeout))
                while self._q.unfinished_tasks:
                    if time.monotonic() >= deadline:
                        self._raise_failure()
                        return False
                    time.sleep(0.01)
        self._raise_failure()
        return True


_writer = AsyncCheckpointer()


def wait_for_checkpoint():
    """Join any in-flight async save (re-raises its failure, if any)."""
    _writer.wait_until_finished()


def _join_writer_at_exit():
    try:
        _writer.wait_until_finished()
    except Exception as e:
        print(f"[checkpoint] async save failed at exit: {e!r}",
              file=sys.stderr)


atexit.register(_join_writer_at_exit)


# ---------------------------------------------------------------------------
# program/scope surface (the reference save_persistables role)
# ---------------------------------------------------------------------------

def _persistable_state(program: Program, scope: Scope) -> Dict[str, Any]:
    state = {}
    for var in program.global_block().vars.values():
        if getattr(var, "persistable", False):
            v = scope.find_var(var.name)
            if v is not None:
                state[var.name] = v
    step = scope.find_var("@STEP_COUNTER@")
    if step is not None:
        state["@STEP_COUNTER@"] = np.asarray(step)
    return state


def _read_seq(path: str) -> int:
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return int(json.load(f).get("seq", 0))
    except (OSError, ValueError):
        return 0


def save_checkpoint(path: str, program: Optional[Program] = None,
                    scope: Optional[Scope] = None, async_save: bool = False,
                    extras: Optional[Dict[str, Any]] = None):
    """Commit all persistables (+ @STEP_COUNTER@, RNG state, `extras`) to
    `path` as one verified checkpoint directory.

    async_save=True returns immediately; the write completes on the
    background writer (call wait_for_checkpoint() to join — an atexit
    hook joins it on interpreter exit regardless)."""
    program = program or default_main_program()
    scope = scope or global_scope()
    state = _persistable_state(program, scope)
    if not state:
        raise ValueError("no persistable state in scope — run the startup "
                         "program first")
    path = os.path.abspath(path)
    step = 0
    if "@STEP_COUNTER@" in state:
        step = int(np.asarray(state["@STEP_COUNTER@"]).reshape(-1)[0])
    seq = _read_seq(path) + 1
    sh = _sharding_manifest_extras(program)
    if sh is not None:
        extras = dict(extras or {})
        extras.setdefault("sharding", sh)
    host = {k: _to_host(v) for k, v in state.items()}
    if async_save:
        _writer.submit(lambda: write_checkpoint_dir(path, host, extras,
                                                    step=step, seq=seq))
    else:
        write_checkpoint_dir(path, host, extras, step=step, seq=seq)
    return path


def load_checkpoint(path: str, program: Optional[Program] = None,
                    scope: Optional[Scope] = None) -> int:
    """Verify + restore persistables (and the saved RNG state) into the
    scope. Raises CheckpointCorruptError instead of loading torn or
    corrupt bytes. Returns the restored step."""
    program = program or default_main_program()
    scope = scope or global_scope()
    try:
        arrays, manifest = read_checkpoint_dir(os.path.abspath(path))
    except CheckpointCorruptError:
        telemetry.counter_add("ckpt.verify_failures", 1,
                              ckpt=os.path.basename(str(path)))
        raise
    _regroup_zero(arrays, program, scope)
    for name, val in arrays.items():
        scope.set(name, val)
    _restore_rng(manifest.get("extras"))
    _note_resharding(manifest.get("extras"))
    return int(manifest.get("step", 0))


def _regroup_zero(arrays, program, scope):
    """World-size-changing resume: re-pad saved ZeRO optimizer-shard
    state to the restoring program's shard geometry (a checkpoint's
    padded length is a function of the dp degree it was saved under —
    parallel/zero_regroup.py)."""
    if program is None or not getattr(program, "_zero_state_numel", None):
        return
    from .parallel import zero_regroup

    zero_regroup.regroup_state(arrays, program, scope)


# ---------------------------------------------------------------------------
# model publishing + manifest watching (the serving control plane's feed)
# ---------------------------------------------------------------------------
#
# A trained model reaches the serving fleet the same way a checkpoint
# reaches a restart: staged, manifested, fsynced, atomically renamed. A
# "published model" is an inference-model dir (io.save_inference_model
# layout) committed under <models_root>/model-<version>/ with a
# MANIFEST.json COMMIT record listing every file's sha256 — so a watcher
# (serving/cluster.py's rolling-swap driver) can poll the root and trust
# that any version it sees is COMPLETE, verified bytes, never a
# half-copied directory.

MODEL_FORMAT = "paddle_tpu-model-v1"
_MODEL_PREFIX = "model-"
_TMP_MODEL_PREFIX = ".tmp-model-"


def list_model_versions(models_root: str) -> List[Tuple[int, str]]:
    """[(version, path)] of committed-named model dirs, ascending. Only
    the NAME is checked here — verify_model_dir() judges the contents."""
    out = []
    try:
        names = os.listdir(models_root)
    except OSError:
        return out
    for name in names:
        if not name.startswith(_MODEL_PREFIX):
            continue
        try:
            version = int(name[len(_MODEL_PREFIX):])
        except ValueError:
            continue
        out.append((version, os.path.join(models_root, name)))
    return sorted(out)


def publish_model(models_root: str, src_dir: str,
                  version: Optional[int] = None,
                  extras: Optional[Dict[str, Any]] = None) -> str:
    """Atomically publish the inference-model dir ``src_dir`` as
    ``<models_root>/model-<version>/`` with a COMMIT manifest.

    Same crash-safety contract as write_checkpoint_dir: every file is
    copied into a staging dir and fsynced, the manifest (per-file sha256
    + nbytes, committed marker) is written last, then one atomic rename.
    ``version`` defaults to newest-on-disk + 1. Returns the final dir."""
    t0 = time.perf_counter()
    models_root = os.path.abspath(models_root)
    os.makedirs(models_root, exist_ok=True)
    if version is None:
        published = list_model_versions(models_root)
        version = (published[-1][0] + 1) if published else 1
    version = int(version)
    final_dir = os.path.join(models_root, f"{_MODEL_PREFIX}{version:06d}")
    names = sorted(n for n in os.listdir(src_dir)
                   if os.path.isfile(os.path.join(src_dir, n)))
    if not names:
        raise ValueError(f"{src_dir}: no model files to publish")
    tmp = os.path.join(models_root,
                       f"{_TMP_MODEL_PREFIX}{version:06d}"
                       f"-{os.getpid()}-{threading.get_ident()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        files = {}
        for name in names:
            dst = os.path.join(tmp, name)
            shutil.copyfile(os.path.join(src_dir, name), dst)
            with open(dst, "rb") as f:
                f.flush()
                os.fsync(f.fileno())
            files[name] = {"sha256": _sha256_file(dst),
                           "nbytes": os.path.getsize(dst)}
        manifest = {
            "format": MODEL_FORMAT,
            "version": version,
            "ts": time.time(),
            "files": files,
            "extras": dict(extras or {}),
            "committed": True,
        }
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        if os.path.exists(final_dir):
            raise CheckpointError(
                f"{final_dir}: model version {version} already published "
                f"(versions are immutable — publish a new one)")
        os.rename(tmp, final_dir)
        _fsync_dir(models_root)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    telemetry.counter_add("serving.models_published", 1, version=version)
    telemetry.observe("ckpt.publish_ms", (time.perf_counter() - t0) * 1e3,
                      kind="timer")
    return final_dir


def verify_model_dir(path: str, deep: Optional[bool] = None) -> Dict[str, Any]:
    """Verify a published model dir's COMMIT manifest (and, with deep
    verification — FLAGS_ckpt_verify default — every file's size +
    sha256). Raises CheckpointCorruptError; returns the manifest."""
    if deep is None:
        deep = bool(_flags.flag("ckpt_verify"))
    if not os.path.isdir(path):
        raise CheckpointCorruptError(f"{path}: not a model directory")
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise CheckpointCorruptError(
            f"{path}: no {MANIFEST_NAME} — publish never committed")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: {e}")
    if manifest.get("format") != MODEL_FORMAT:
        raise CheckpointCorruptError(
            f"{path}: unknown model format {manifest.get('format')!r}")
    if not manifest.get("committed"):
        raise CheckpointCorruptError(f"{path}: manifest lacks commit marker")
    for name, spec in (manifest.get("files") or {}).items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            raise CheckpointCorruptError(f"{path}: model file '{name}' "
                                         f"missing")
        if deep:
            nbytes = os.path.getsize(fpath)
            if nbytes != int(spec.get("nbytes", -1)):
                raise CheckpointCorruptError(
                    f"{path}: torn model file '{name}' ({nbytes} bytes, "
                    f"manifest says {spec.get('nbytes')})")
            if _sha256_file(fpath) != spec.get("sha256"):
                raise CheckpointCorruptError(
                    f"{path}: sha256 mismatch for model file '{name}'")
    return manifest


class ModelWatcher:
    """Poll a models root for newly published VERIFIED versions — the
    manifest-watch helper behind the serving control plane's
    zero-downtime swap (a new committed version appearing under the root
    is the signal to roll the replica fleet onto it).

    ``latest()`` returns the newest (version, path) whose manifest
    verifies — an unverifiable candidate is skipped (counted on
    ``serving.model_rejected``), falling back to the next-newest, same
    discipline as restore_latest. ``poll()`` returns it only when it is
    NEWER than the last version this watcher reported (None otherwise),
    so a polling loop fires exactly once per published version."""

    def __init__(self, models_root: str,
                 last_version: Optional[int] = None):
        self.models_root = os.path.abspath(models_root)
        self.last_version = last_version

    def latest(self) -> Optional[Tuple[int, str]]:
        for version, path in reversed(list_model_versions(self.models_root)):
            try:
                verify_model_dir(path)
            except CheckpointCorruptError as e:
                telemetry.counter_add("serving.model_rejected", 1,
                                      version=version,
                                      reason=type(e).__name__)
                continue
            return version, path
        return None

    def poll(self) -> Optional[Tuple[int, str]]:
        newest = self.latest()
        if newest is None:
            return None
        if self.last_version is not None and \
                newest[0] <= self.last_version:
            return None
        self.last_version = newest[0]
        return newest


class CheckpointManager:
    """Retention + auto-resume driver over the atomic-commit protocol
    (reference: hapi ModelCheckpoint + the PS checkpoint_notify flow).

    mgr = CheckpointManager(dir, max_to_keep=3)
    start = mgr.restore_latest(program, scope)      # 0 if fresh
    for step in range(start, N):
        ...train...
        mgr.save(step, program, scope)              # honors save_interval

    restore_latest quarantines any candidate that fails verification and
    falls back to the newest one that passes; `last_restore_extras`
    exposes the restored snapshot's extras (reader cursor, epoch, ...).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = int(max_to_keep)
        self.save_interval = max(1, int(save_interval_steps))
        self.async_save = bool(async_save)
        self._last_saved: Optional[int] = None
        self.last_restore_extras: Dict[str, Any] = {}
        # the monotonic save sequence resumes past anything on disk
        self._seq = max([_read_seq(p) for _, p in self._candidates()],
                        default=0)

    # -- directory scanning --------------------------------------------------
    def _candidates(self) -> List[Tuple[int, str]]:
        """[(step, path)] of committed-named checkpoint dirs, ascending."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.startswith(_CKPT_PREFIX):
                continue
            try:
                step = int(name[len(_CKPT_PREFIX):])
            except ValueError:
                continue
            out.append((step, os.path.join(self.directory, name)))
        return sorted(out)

    def _sweep_uncommitted(self):
        """Quarantine staging dirs a killed save left behind."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if name.startswith(_TMP_PREFIX):
                telemetry.counter_add("ckpt.verify_failures", 1,
                                      ckpt=name, reason="uncommitted")
                quarantine_checkpoint(os.path.join(self.directory, name),
                                      "uncommitted")

    def all_steps(self) -> List[int]:
        return [s for s, _ in self._candidates()]

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save ----------------------------------------------------------------
    def save_arrays(self, step: int, arrays: Dict[str, Any],
                    extras: Optional[Dict[str, Any]] = None,
                    force: bool = False) -> bool:
        """Arrays-level save (hapi training snapshots, PS tables). The
        host snapshot is taken HERE so async writes see this step's
        values even if training keeps mutating/donating buffers."""
        step = int(step)
        if not force and self._last_saved is not None and \
                step - self._last_saved < self.save_interval:
            return False
        host = {k: _to_host(v) for k, v in arrays.items()}
        self._seq += 1
        seq = self._seq
        self._last_saved = step
        path = os.path.join(self.directory, f"{_CKPT_PREFIX}{step:010d}")

        def job():
            write_checkpoint_dir(path, host, extras, step=step, seq=seq)
            self._retain()

        if self.async_save:
            _writer.submit(job)
        else:
            job()
        return True

    def save(self, step: int, program: Optional[Program] = None,
             scope: Optional[Scope] = None,
             extras: Optional[Dict[str, Any]] = None,
             force: bool = False) -> bool:
        program = program or default_main_program()
        state = _persistable_state(program, scope or global_scope())
        if not state:
            raise ValueError("no persistable state in scope — run the "
                             "startup program first")
        sh = _sharding_manifest_extras(program)
        if sh is not None:
            extras = dict(extras or {})
            extras.setdefault("sharding", sh)
        return self.save_arrays(step, state, extras=extras, force=force)

    def _retain(self):
        if self.max_to_keep <= 0:
            return
        dirs = self._candidates()
        for _, path in dirs[:-self.max_to_keep]:
            shutil.rmtree(path, ignore_errors=True)

    # -- restore -------------------------------------------------------------
    def restore_latest_arrays(self) -> Tuple[int, Dict[str, np.ndarray],
                                             Dict[str, Any]]:
        """Newest checkpoint that VERIFIES: (step, arrays, extras) —
        (0, {}, {}) when none. Rejected candidates are quarantined; the
        restored snapshot's RNG state is applied."""
        self.wait_until_finished()
        self._sweep_uncommitted()
        rejected = 0
        for step, path in reversed(self._candidates()):
            try:
                arrays, manifest = read_checkpoint_dir(path)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # anything unreadable is untrustworthy: quarantine it and
                # fall through to the next-newest candidate
                telemetry.counter_add("ckpt.verify_failures", 1, step=step,
                                      reason=type(e).__name__)
                quarantine_checkpoint(path, type(e).__name__)
                rejected += 1
                continue
            if rejected:
                telemetry.counter_add("ckpt.fallbacks", 1, step=step,
                                      skipped=rejected)
            extras = manifest.get("extras") or {}
            _restore_rng(extras)
            _note_resharding(extras)
            self.last_restore_extras = extras
            self._last_saved = int(manifest.get("step", step))
            return self._last_saved, arrays, extras
        return 0, {}, {}

    def restore_latest(self, program: Optional[Program] = None,
                       scope: Optional[Scope] = None) -> int:
        """Load the newest VERIFIED checkpoint if any; returns its step
        (0 if none). This is the failure-recovery entry point: rerun the
        same script and training resumes."""
        scope = scope or global_scope()
        step, arrays, _ = self.restore_latest_arrays()
        _regroup_zero(arrays, program, scope)
        for name, val in arrays.items():
            scope.set(name, val)
        return int(step)

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        if self.async_save:
            return _writer.wait_until_finished(timeout=timeout)
        return True

    def close(self):
        self.wait_until_finished()
