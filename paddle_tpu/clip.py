"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue:186, GradientClipByNorm:261, and
GradientClipByGlobalNorm:341; 2.0 re-exports them as nn.ClipGradBy*).

Each class is a callable over params_grads, invoked by the Optimizer
between backward() and apply_gradients() (optimizer/__init__.py), and
dual-mode like every layer: ops append to the current program in static
mode and execute eagerly under dygraph.guard.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

__all__ = ["GradientClipBase", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm"]


class GradientClipBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    """g <- clamp(g, min, max); min defaults to -max (reference
    clip.py:186)."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        from . import layers

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.clip(g, self.min, self.max)))
        return out


class GradientClipByNorm(GradientClipBase):
    """Per-gradient L2 clip: g <- g * clip_norm / max(||g||, clip_norm)
    (reference clip.py:261 — each grad clipped independently)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        from . import layers

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = layers.sqrt(layers.reduce_sum(layers.square(g)))
            denom = layers.elementwise_max(
                norm, layers.fill_constant([1], "float32", self.clip_norm))
            out.append((p, g * (self.clip_norm / denom)))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    """Joint clip: scale every grad by clip_norm / max(global_norm,
    clip_norm) with global_norm = sqrt(sum_i ||g_i||^2) (reference
    clip.py:341 — the transformer-training staple)."""

    def __init__(self, clip_norm, group_name: str = "default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        from . import layers

        sq_sums = [layers.reduce_sum(layers.square(g))
                   for _, g in params_grads if g is not None]
        if not sq_sums:
            return list(params_grads)
        total = sq_sums[0]
        for s in sq_sums[1:]:
            total = total + s
        global_norm = layers.sqrt(total)
        denom = layers.elementwise_max(
            global_norm,
            layers.fill_constant([1], "float32", self.clip_norm))
        scale = self.clip_norm / denom
        out = []
        for p, g in params_grads:
            out.append((p, g if g is None else g * scale))
        return out


# 2.0 names (python/paddle/nn/clip.py aliases)
ClipGradByValue = GradientClipByValue
ClipGradByNorm = GradientClipByNorm
ClipGradByGlobalNorm = GradientClipByGlobalNorm
