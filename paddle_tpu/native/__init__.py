"""Native (C++) runtime bindings — build-on-first-use + ctypes.

The C++ sources live in <repo>/native/ (data_feed.cc: the reference's
data_feed.cc / data_set.cc / channel.h capability as one library). The
shared object is compiled with g++ on first import (no pybind11 in the
image — C ABI + ctypes) and cached next to the sources keyed on a source
hash. `available()` is False when no toolchain exists; callers fall back
to the pure-Python parser (dataset.py) so the framework never hard-depends
on the native path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.analysis import lockdep as _lockdep

_LOCK = _lockdep.lock("native.build")
_LIB = None
_ERR: Optional[str] = None

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SOURCES = ["data_feed.cc"]


def _build_and_load():
    global _LIB, _ERR
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    if not all(os.path.exists(s) for s in srcs):
        _ERR = f"native sources not found under {_SRC_DIR}"
        return
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    so_path = os.path.join(_SRC_DIR, f"libpaddle_tpu_native.{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        # compile to a process-unique temp path then atomically rename so a
        # concurrent process never CDLLs a half-written file
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               "-o", tmp_path] + srcs
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True,
                           timeout=300)
            os.replace(tmp_path, so_path)
        except FileNotFoundError:
            _ERR = "g++ not found"
            return
        except subprocess.CalledProcessError as e:
            _ERR = f"native build failed:\n{e.stderr[-2000:]}"
            return
        finally:
            if os.path.exists(tmp_path):
                try:
                    os.remove(tmp_path)
                except OSError:
                    pass
    lib = ctypes.CDLL(so_path)
    lib.ptds_create.restype = ctypes.c_void_p
    lib.ptds_create.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                ctypes.c_char_p, ctypes.c_int]
    lib.ptds_destroy.argtypes = [ctypes.c_void_p]
    lib.ptds_set_filelist.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_char_p),
                                      ctypes.c_int]
    lib.ptds_last_error.restype = ctypes.c_char_p
    lib.ptds_last_error.argtypes = [ctypes.c_void_p]
    lib.ptds_load_into_memory.restype = ctypes.c_long
    lib.ptds_load_into_memory.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptds_global_shuffle.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ptds_num_records.restype = ctypes.c_long
    lib.ptds_num_records.argtypes = [ctypes.c_void_p]
    lib.ptds_begin_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptds_next_batch.restype = ctypes.c_long
    lib.ptds_next_batch.argtypes = [ctypes.c_void_p]
    lib.ptds_slot_values.restype = ctypes.c_long
    lib.ptds_slot_values.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_void_p)]
    lib.ptds_slot_lod.restype = ctypes.c_long
    lib.ptds_slot_lod.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
    lib.ptds_stat_mem_bytes.restype = ctypes.c_uint64
    lib.ptds_stat_records_parsed.restype = ctypes.c_uint64
    lib.ptds_stream_begin.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_int]
    lib.ptds_stream_next_batch.restype = ctypes.c_long
    lib.ptds_stream_next_batch.argtypes = [ctypes.c_void_p]
    lib.ptds_stream_end.argtypes = [ctypes.c_void_p]
    _LIB = lib


def get_lib():
    global _LIB
    with _LOCK:
        if _LIB is None and _ERR is None:
            # pt-lint: disable=blocking-call-under-lock(one-time g++ build on first use; concurrent importers MUST wait for it rather than double-compile)
            _build_and_load()
    return _LIB


def available() -> bool:
    return get_lib() is not None


def loaded() -> bool:
    """True only if the library is ALREADY loaded — never triggers a build
    (observability readers must not block on a g++ subprocess)."""
    return _LIB is not None


def build_error() -> Optional[str]:
    get_lib()
    return _ERR


def mem_bytes() -> int:
    lib = get_lib()
    return int(lib.ptds_stat_mem_bytes()) if lib else 0


def records_parsed() -> int:
    lib = get_lib()
    return int(lib.ptds_stat_records_parsed()) if lib else 0


class NativeDataset:
    """Handle over the C++ MultiSlot in-memory dataset.

    slots: [(name, 'f'|'u'), ...] in file column order."""

    def __init__(self, slots: Sequence[Tuple[str, str]]):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_ERR}")
        self._lib = lib
        self.slots = list(slots)
        names = (ctypes.c_char_p * len(slots))(
            *[s[0].encode() for s in slots])
        types = "".join(s[1] for s in slots).encode()
        self._h = lib.ptds_create(names, types, len(slots))

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.ptds_destroy(self._h)
            self._h = None

    def set_filelist(self, files: Sequence[str]):
        arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
        self._lib.ptds_set_filelist(self._h, arr, len(files))

    def load_into_memory(self, num_threads: int = 4) -> int:
        n = self._lib.ptds_load_into_memory(self._h, num_threads)
        if n < 0:
            raise RuntimeError(
                self._lib.ptds_last_error(self._h).decode() or "load failed")
        return int(n)

    def global_shuffle(self, seed: int = 0):
        self._lib.ptds_global_shuffle(self._h, seed)

    def num_records(self) -> int:
        return int(self._lib.ptds_num_records(self._h))

    def _read_batch(self):
        out = {}
        for idx, (name, typ) in enumerate(self.slots):
            ptr = ctypes.c_void_p()
            n = self._lib.ptds_slot_values(self._h, idx, ctypes.byref(ptr))
            ctype = ctypes.c_float if typ == "f" else ctypes.c_int64
            buf = ctypes.cast(ptr, ctypes.POINTER(ctype * n)).contents \
                if n else (ctype * 0)()
            vals = np.frombuffer(buf, dtype=np.float32 if typ == "f"
                                 else np.int64).copy() if n else \
                np.zeros((0,), np.float32 if typ == "f" else np.int64)
            lod_ptr = ctypes.POINTER(ctypes.c_int64)()
            ln = self._lib.ptds_slot_lod(self._h, idx, ctypes.byref(lod_ptr))
            lod = np.ctypeslib.as_array(lod_ptr, shape=(ln,)).copy()
            out[name] = (vals, lod)
        return out

    def batches(self, batch_size: int):
        """Yield {slot: (values ndarray, lod ndarray)} per batch from the
        in-memory store. Values are copied out of the native buffers
        (they are reused next batch)."""
        self._lib.ptds_begin_epoch(self._h, batch_size)
        while True:
            rows = self._lib.ptds_next_batch(self._h)
            if rows <= 0:
                return
            yield self._read_batch()

    def stream_batches(self, batch_size: int, num_threads: int = 4):
        """QueueDataset mode: background parser threads feed a bounded
        channel; batches stream out without materialising the dataset.
        Record order depends on thread interleaving."""
        self._lib.ptds_stream_begin(self._h, batch_size, num_threads)
        try:
            while True:
                rows = self._lib.ptds_stream_next_batch(self._h)
                if rows <= 0:
                    break
                yield self._read_batch()
        finally:
            self._lib.ptds_stream_end(self._h)
        err = self._lib.ptds_last_error(self._h).decode()
        if err:
            raise RuntimeError(f"stream parse failed: {err}")
