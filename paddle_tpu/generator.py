"""RNG generator state (reference: framework/generator.{h,cc} Generator —
global/per-device seed + state get/set; paddle.seed / paddle.get_rng_state).

Program-level randomness here is seed-attr based (ops fold seed + step),
so the generator tracks the global seed used when op seeds are assigned,
plus a counter for unique per-op seeds."""

from __future__ import annotations

from .core.analysis import lockdep as _lockdep


class Generator:
    def __init__(self, seed: int = 0):
        self._lock = _lockdep.lock("generator.state")
        self._seed = seed
        self._offset = 0

    def manual_seed(self, seed: int):
        with self._lock:
            self._seed = int(seed)
            self._offset = 0
        return self

    def seed(self) -> int:
        return self._seed

    def initial_seed(self) -> int:
        return self._seed

    def get_state(self):
        with self._lock:
            return (self._seed, self._offset)

    def set_state(self, state):
        with self._lock:
            self._seed, self._offset = int(state[0]), int(state[1])


_default = Generator()


def default_generator() -> Generator:
    return _default


def seed(value: int):
    """paddle.seed — also seeds the default programs' random_seed (op
    seeds derive from it at build time, core/ir.py next_op_seed)."""
    from .core.ir import default_main_program, default_startup_program

    _default.manual_seed(value)
    default_main_program().random_seed = value
    default_startup_program().random_seed = value
    return _default


def get_rng_state():
    """Snapshot everything that controls build-time randomness: the
    generator seed plus the default programs' (random_seed, op-seed
    counter) — restoring it makes subsequently BUILT random ops repeat."""
    from .core.ir import default_main_program, default_startup_program

    main, startup = default_main_program(), default_startup_program()
    return (_default.get_state(),
            (main.random_seed, main._seed_counter),
            (startup.random_seed, startup._seed_counter))


def set_rng_state(state):
    from .core.ir import default_main_program, default_startup_program

    gen_state, (mseed, mctr), (sseed, sctr) = state
    _default.set_state(gen_state)
    main, startup = default_main_program(), default_startup_program()
    main.random_seed, main._seed_counter = mseed, mctr
    startup.random_seed, startup._seed_counter = sseed, sctr
