"""hapi callbacks (reference: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint; EarlyStopping from the later series)."""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "CallbackList", "TelemetryLogger"]


class Callback:
    def __init__(self):
        self.model = None
        self.params: Dict = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params: Dict):
        self.params = dict(params or {})

    # lifecycle hooks — mode in {train, eval, predict}
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None, model=None,
                 params=None):
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            cb.set_model(model)
            if params is not None:  # don't wipe params set by an outer loop
                cb.set_params(params)

    def _call(self, name, *args, **kw):
        for cb in self.callbacks:
            getattr(cb, name)(*args, **kw)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a, **k: self._call(name, *a, **k)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch progress logging (reference: callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose < 2 or step % self.log_freq:
            return
        logs = logs or {}
        items = " - ".join(f"{k}: {self._fmt(v)}" for k, v in logs.items())
        total = f"/{self.steps}" if self.steps else ""
        print(f"Epoch {self.epoch}: step {step}{total} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose < 1:
            return
        logs = logs or {}
        items = " - ".join(f"{k}: {self._fmt(v)}" for k, v in logs.items())
        dt = time.time() - self._start
        print(f"Epoch {epoch} done ({dt:.1f}s) - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose < 1:
            return
        logs = logs or {}
        items = " - ".join(f"{k}: {self._fmt(v)}" for k, v in logs.items())
        print(f"Eval - {items}")

    @staticmethod
    def _fmt(v):
        a = np.asarray(v, dtype=object)
        try:
            return f"{float(np.asarray(v).reshape(-1)[0]):.4f}"
        except (TypeError, ValueError):
            return str(a)


class TelemetryLogger(Callback):
    """Stream step-level training metrics into ``core.telemetry``: per-step
    wall time (the ``hapi.step_ms`` histogram → step-time percentiles in
    ``tools/perf_report.py``), steps/s throughput, and the scalar logs
    (loss/metrics) as JSONL ``step`` events when a run log is enabled
    (``FLAGS_telemetry_path`` / ``PT_TELEMETRY_LOG``). ``Model.fit``
    attaches one automatically whenever the sink is enabled."""

    def __init__(self, every: int = 1):
        super().__init__()
        self.every = max(1, int(every))
        self._t0 = None
        self._epoch = 0

    @staticmethod
    def _scalars(logs):
        out = {}
        for k, v in (logs or {}).items():
            try:
                out[k] = float(np.asarray(v).reshape(-1)[0])
            except (TypeError, ValueError):
                pass
        return out

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        from ..core import telemetry

        if self._t0 is None:
            return
        ms = (time.perf_counter() - self._t0) * 1e3
        self._t0 = None
        telemetry.observe("hapi.step_ms", ms, kind="timer")
        telemetry.counter_add("hapi.train_steps", 1)
        if step % self.every:
            return
        attrs = {"epoch": self._epoch, "step": int(step),
                 "ms": round(ms, 3)}
        if ms > 0:
            attrs["steps_per_s"] = round(1e3 / ms, 3)
        attrs.update(self._scalars(logs))
        telemetry.event("step", "train", attrs.get("loss"), attrs)

    def on_eval_end(self, logs=None):
        from ..core import telemetry

        attrs = self._scalars(logs)
        telemetry.counter_add("hapi.evals", 1)
        telemetry.event("step", "eval",
                        attrs.get("eval_loss", attrs.get("loss")), attrs)


class ModelCheckpoint(Callback):
    """Save params (+opt state) every `save_freq` epochs into
    `save_dir/{epoch}` and `save_dir/final` (reference: ModelCheckpoint).
    Writes go through dygraph.save_dygraph, whose npz + manifest files
    commit atomically (io.atomic_savez/atomic_write_json) — a process
    killed mid-save can't leave a torn epoch directory. For exact crash
    resume (optimizer + RNG + epoch cursor) use Model.fit(resume_from=)
    instead, which snapshots through the verified checkpoint protocol."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, min_delta: float = 0.0,
                 baseline: Optional[float] = None,
                 save_best_model: bool = True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _improved(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        # eval logs are 'eval_'-prefixed; accept the bare reference-style
        # monitor name ('loss', 'acc') as well
        key = self.monitor if self.monitor in logs else "eval_" + self.monitor
        if key not in logs:
            return
        cur = float(np.asarray(logs[key]).reshape(-1)[0])
        if self._improved(cur):
            self.best = cur
            self.wait = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and self.model is not None and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True
