"""hapi — Keras-like high-level API (reference: python/paddle/hapi/)."""

from .callbacks import (Callback, EarlyStopping, ModelCheckpoint,  # noqa: F401
                        ProgBarLogger)
from .model import Model  # noqa: F401
from .model_summary import summary  # noqa: F401
