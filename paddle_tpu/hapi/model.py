"""hapi Model — Keras-like train/eval/predict driver.

Capability mirror of the reference (python/paddle/hapi/model.py: Model:799,
prepare:1211, fit:1267, train_batch:879, evaluate, predict, save/load).
The reference carries two adapters (static graph + dygraph); here the
dygraph adapter is the single path — the eager tracer already jit-fuses the
per-step update, and static-graph users drive Program/Executor directly.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import dygraph
from ..dygraph import to_variable
from ..metric import Metric
from ..reader import DataLoader, Dataset
from .callbacks import Callback, CallbackList, ProgBarLogger, TelemetryLogger

__all__ = ["Model"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _as_list(inputs)
        self._labels = _as_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- setup ----------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = _as_list(metrics)
        for m in metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be Metric instances, got {m}")
        self._metrics = metrics
        return self

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        """Per-layer table (reference: hapi/model.py Model.summary) —
        input_size defaults to the shapes of the Model's input specs."""
        from .model_summary import summary as _summary

        if input_size is None:
            shapes = [tuple(getattr(i, "shape", ())) for i in self._inputs]
            if not shapes or not all(shapes):
                raise ValueError(
                    "summary needs input_size (the Model was built "
                    "without input specs carrying shapes)")
            input_size = shapes
        return _summary(self.network, input_size, dtypes=dtype)

    # -- one-batch ops --------------------------------------------------------
    def _compute_loss(self, outputs, labels):
        return self._loss(*_as_list(outputs), *_as_list(labels))

    def train_batch(self, inputs, labels=None, sync: bool = True):
        """One optimizer step. sync=False skips the loss's device→host
        round trip — the returned loss is a device array and the step's
        dispatch stays async (XLA keeps computing while Python moves on);
        materialization is deferred to whoever formats the value (the
        callback layer at its log cadence). Metrics always accumulate on
        host, so passing metrics forces a sync regardless.
        """
        if self._loss is None or self._optimizer is None:
            raise RuntimeError("call prepare(optimizer, loss) before training")
        self.network.train()
        ins = [to_variable(np.asarray(v)) for v in _as_list(inputs)]
        lbls = [to_variable(np.asarray(v)) for v in _as_list(labels)]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, lbls)
        loss.backward()
        self._optimizer.minimize(loss)
        self.network.clear_gradients()
        metrics = []
        for m in self._metrics:
            m.update(*_as_list(outputs), *lbls)
            metrics.append(m.accumulate())
        loss_out = loss._array if not sync else \
            float(np.asarray(loss.numpy()).reshape(-1)[0])
        return ([loss_out], metrics) if metrics else [loss_out]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        with dygraph.no_grad():
            ins = [to_variable(np.asarray(v)) for v in _as_list(inputs)]
            lbls = [to_variable(np.asarray(v)) for v in _as_list(labels)]
            outputs = self.network(*ins)
            losses = []
            if self._loss is not None and lbls:
                loss = self._compute_loss(outputs, lbls)
                losses = [float(np.asarray(loss.numpy()).reshape(-1)[0])]
            metrics = []
            for m in self._metrics:
                m.update(*_as_list(outputs), *lbls)
                metrics.append(m.accumulate())
        return (losses, metrics) if metrics else losses

    def predict_batch(self, inputs):
        self.network.eval()
        with dygraph.no_grad():
            ins = [to_variable(np.asarray(v)) for v in _as_list(inputs)]
            outputs = self.network(*ins)
        return [o.numpy() for o in _as_list(outputs)]

    # -- loops ----------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        return data  # assume iterable of batches

    def _split_batch(self, batch):
        batch = _as_list(batch)
        n_in = max(len(self._inputs), 1) if self._inputs else len(batch) - 1
        if len(batch) == 1:
            return batch, []
        return batch[:n_in], batch[n_in:]

    def fit(self, train_data=None, eval_data=None, batch_size: int = 1,
            epochs: int = 1, eval_freq: int = 1, log_freq: int = 10,
            save_dir: Optional[str] = None, save_freq: int = 1,
            verbose: int = 2, drop_last: bool = False, shuffle: bool = True,
            num_workers: int = 0, callbacks: Optional[List[Callback]] = None,
            resume_from: Optional[str] = None):
        """resume_from names a crash-consistency directory: fit restores
        the newest VERIFIED training snapshot in it (network + optimizer
        state incl. LR, global RNG state, completed-epoch count — torn or
        corrupt snapshots are quarantined and skipped) and commits a new
        atomic snapshot after every epoch. Re-running the same fit() call
        after a crash continues exactly where the dead run left off; with
        a deterministic data order (shuffle=False or a seeded sampler)
        the resumed run matches an uninterrupted one bitwise."""
        loader = self._make_loader(train_data, batch_size, shuffle)
        # async-dispatch cadence: the loss only crosses to the host on
        # log steps (every log_freq batches) — per-batch float() syncs
        # serialized the device pipeline. Metrics force a host sync every
        # batch anyway, so they keep the synchronous path. With
        # FLAGS_exec_steps_per_dispatch=k the sync cadence additionally
        # aligns to k-step windows (the eager twin of run_steps fusion)
        from ..core.flags import flag as _flag

        k = max(1, int(_flag("exec_steps_per_dispatch")))
        sync_every = max(1, int(log_freq or 1), k)
        force_sync = bool(self._metrics)
        cbks = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbks):
            cbks.append(ProgBarLogger(log_freq, verbose=verbose))
        if save_dir:
            from .callbacks import ModelCheckpoint

            cbks.append(ModelCheckpoint(save_freq, save_dir))
        from ..core import telemetry

        if telemetry.enabled() and \
                not any(isinstance(c, TelemetryLogger) for c in cbks):
            # scalar JSONL step events only on sync steps — a per-step
            # TelemetryLogger would float() the async losses back into
            # per-batch syncs
            cbks.append(TelemetryLogger(every=sync_every))
        steps = len(loader) if hasattr(loader, "__len__") else None
        cb = CallbackList(cbks, model=self,
                          params={"epochs": epochs, "steps": steps,
                                  "verbose": verbose, "save_dir": save_dir,
                                  "metrics": self._metrics_names()})
        self.stop_training = False
        ckpt_mgr = None
        if resume_from:
            from ..checkpoint import CheckpointManager

            ckpt_mgr = CheckpointManager(resume_from, max_to_keep=3,
                                         async_save=False)
        with dygraph.guard():
            start_epoch = 0
            if ckpt_mgr is not None:
                start_epoch = self._restore_training_state(ckpt_mgr)
            cb.on_train_begin()
            logs: Dict[str, Any] = {}
            for epoch in range(start_epoch, epochs):
                cb.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                for step, batch in enumerate(loader):
                    cb.on_train_batch_begin(step)
                    ins, lbls = self._split_batch(batch)
                    result = self.train_batch(
                        ins, lbls,
                        sync=force_sync or step % sync_every == 0)
                    logs = self._result_logs(result)
                    cb.on_train_batch_end(step, logs)
                cb.on_epoch_end(epoch, logs)
                if eval_data is not None and epoch % eval_freq == 0:
                    self.evaluate(eval_data, batch_size=batch_size,
                                  verbose=verbose, callbacks=cbks,
                                  num_workers=num_workers)
                if ckpt_mgr is not None:
                    self._save_training_state(ckpt_mgr, epoch)
                if self.stop_training:
                    break
            cb.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size: int = 1, log_freq: int = 10,
                 verbose: int = 2, num_workers: int = 0,
                 callbacks: Optional[List[Callback]] = None):
        loader = self._make_loader(eval_data, batch_size, shuffle=False)
        cb = CallbackList(list(callbacks or []), model=self)
        with dygraph.guard():
            cb.on_eval_begin()
            for m in self._metrics:
                m.reset()
            logs: Dict[str, Any] = {}
            losses = []
            for step, batch in enumerate(loader):
                cb.on_eval_batch_begin(step)
                ins, lbls = self._split_batch(batch)
                result = self.eval_batch(ins, lbls)
                logs = self._result_logs(result, prefix="eval_")
                if isinstance(result, tuple):
                    losses.extend(result[0])
                else:
                    losses.extend(result)
                cb.on_eval_batch_end(step, logs)
            if losses:
                logs["eval_loss"] = float(np.mean(losses))
            cb.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size: int = 1,
                stack_outputs: bool = False):
        loader = self._make_loader(test_data, batch_size, shuffle=False)
        outs: List[List[np.ndarray]] = []
        with dygraph.guard():
            for batch in loader:
                ins, _ = self._split_batch(batch)
                outs.append(self.predict_batch(ins))
        n_out = len(outs[0]) if outs else 0
        grouped = [[b[i] for b in outs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # -- crash-consistent training snapshots (fit(resume_from=...)) ----------
    def _training_state_arrays(self) -> Dict[str, np.ndarray]:
        """One flat array dict for the atomic checkpoint protocol:
        'net:<structured name>' for network params/buffers, 'opt:<key>'
        for the optimizer's positional state (accumulators + LR)."""
        arrays = {}
        for k, v in self.network.state_dict().items():
            arrays["net:" + k] = np.asarray(
                v.numpy() if hasattr(v, "numpy") else v)
        if self._optimizer is not None and hasattr(self._optimizer,
                                                   "state_dict"):
            for k, v in self._optimizer.state_dict().items():
                arrays["opt:" + k] = np.asarray(v)
        return arrays

    def _save_training_state(self, mgr, epoch: int):
        mgr.save_arrays(epoch + 1, self._training_state_arrays(),
                        extras={"epoch": int(epoch + 1)})

    def _restore_training_state(self, mgr) -> int:
        """Restore the newest verified snapshot; returns the epoch to
        resume at (0 when the directory is fresh). The manager applies
        the snapshot's RNG state; optimizer state restores through the
        pending-state path if no step has built the micro-program yet."""
        step, arrays, extras = mgr.restore_latest_arrays()
        if not step:
            return 0
        net = {k[4:]: v for k, v in arrays.items() if k.startswith("net:")}
        opt = {k[4:]: v for k, v in arrays.items() if k.startswith("opt:")}
        if net:
            self.network.set_state_dict(net)
        if opt and self._optimizer is not None and \
                hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(opt)
        return int(extras.get("epoch", step))

    # -- persistence ----------------------------------------------------------
    def save(self, path: str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        dygraph.save_dygraph(self.network.state_dict(), path)
        if self._optimizer is not None and hasattr(self._optimizer,
                                                   "state_dict"):
            dygraph.save_dygraph(self._optimizer.state_dict(), path)

    def load(self, path: str, skip_mismatch: bool = False,
             reset_optimizer: bool = False):
        params, opt_state = dygraph.load_dygraph(path)
        if params is not None:
            self.network.set_state_dict(params)
        if not reset_optimizer and opt_state and self._optimizer is not None \
                and hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(opt_state)
        return self

    # -- helpers --------------------------------------------------------------
    def _metrics_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _result_logs(self, result, prefix=""):
        logs: Dict[str, Any] = {}
        if isinstance(result, tuple):
            losses, metrics = result
            logs[prefix + "loss"] = losses[0] if losses else None
            for m, v in zip(self._metrics, metrics):
                n = m.name()
                if isinstance(n, list):
                    for ni, vi in zip(n, _as_list(v)):
                        logs[prefix + ni] = vi
                else:
                    logs[prefix + n] = v
        else:
            logs[prefix + "loss"] = result[0] if result else None
        return logs
