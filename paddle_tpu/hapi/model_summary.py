"""Layer-wise model summary (reference: hapi/model_summary.py —
summary(net, input_size) walks the Layer tree with forward hooks,
printing each layer's output shape and parameter count and returning
{'total_params', 'trainable_params'})."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["summary"]


def _as_size_list(input_size):
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        return [tuple(s) for s in input_size]
    return [tuple(input_size)]


def _shape_of(out):
    if isinstance(out, (list, tuple)):
        return [_shape_of(o) for o in out]
    shp = getattr(out, "shape", None)
    return list(shp) if shp is not None else None


def summary(net, input_size, dtypes=None):
    """Print a per-layer table for a dygraph Layer by running one
    forward pass on zero inputs of `input_size` (one shape tuple, or a
    list of them for multi-input nets; a leading -1/None batch dim
    becomes 1). Returns {'total_params': int, 'trainable_params': int}.
    """
    from .. import dygraph
    from ..dygraph.layers import Layer

    sizes = _as_size_list(input_size)
    if dtypes is None:
        dtypes = ["float32"] * len(sizes)
    elif isinstance(dtypes, str):
        dtypes = [dtypes] * len(sizes)
    if len(dtypes) != len(sizes):
        raise ValueError(
            f"dtypes length ({len(dtypes)}) must match the number of "
            f"input shapes ({len(sizes)})")

    rows: List[dict] = []
    handles = []

    def make_hook(name, layer):
        def hook(lyr, ins, out):
            n_params = sum(
                int(np.prod(p.shape)) if p.shape else 1
                for p in layer.parameters(include_sublayers=False))
            rows.append({
                "name": f"{type(layer).__name__}-{name}" if name
                        else type(layer).__name__,
                "output_shape": _shape_of(out),
                "params": n_params,
            })

        return hook

    for name, layer in net.named_sublayers(include_self=False):
        handles.append(layer.register_forward_post_hook(
            make_hook(name, layer)))

    was_dygraph = dygraph.enabled()
    # summary must not flip a net being trained into eval as a side
    # effect — remember each sublayer's mode and restore it
    modes = [(lyr, lyr.training)
             for lyr in net.sublayers(include_self=True)]
    try:
        if not was_dygraph:
            dygraph.enable_dygraph()
        from .. import to_tensor

        feeds = []
        for shp, dt in zip(sizes, dtypes):
            shp = tuple(1 if (d is None or int(d) < 0) else int(d)
                        for d in shp)
            feeds.append(to_tensor(np.zeros(shp, dtype=dt)))
        with dygraph.no_grad():
            net.eval()
            net(*feeds)
    finally:
        for h in handles:
            h.remove()
        for lyr, training in modes:
            lyr.training = training
        if not was_dygraph:
            dygraph.disable_dygraph()

    # parameters owned by layers whose forward never fired (e.g. shared
    # tables used functionally) still count toward the totals
    total = trainable = 0
    for p in net.parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not getattr(p, "stop_gradient", False):
            trainable += n

    width = max([len(r["name"]) for r in rows] + [12])
    print(f"{'Layer (type)':<{width + 2}}{'Output Shape':<26}{'Param #':>12}")
    print("=" * (width + 40))
    for r in rows:
        print(f"{r['name']:<{width + 2}}"
              f"{str(r['output_shape']):<26}{r['params']:>12,}")
    print("=" * (width + 40))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
