"""Control-flow layer surface: cond / while_loop / static_loop.

Capability mirror of python/paddle/fluid/layers/control_flow.py (cond,
While/while_loop, StaticRNN) over the sub-block ops in
ops/control_flow_ops.py. Branch/body functions are traced into child
Blocks of the current program (the reference's sub-block mechanism,
conditional_block_op.cc / while_op.cc) and lowered to lax.cond /
lax.while_loop / lax.scan.

Differentiability: `cond` and `static_loop` differentiate through the
generic vjp grad maker (lax.cond/scan support reverse AD);
`while_loop` does NOT (lax.while_loop is forward-only in XLA) — use
static_loop when the trip count is static and gradients are needed,
mirroring the reference's StaticRNN-vs-While split.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

from ..core.ir import Block, Variable, default_main_program

from ..layer_helper import LayerHelper


def _as_list(v):
    if v is None:
        return []
    return list(v) if isinstance(v, (list, tuple)) else [v]


def _trace_sub_block(fn, args=()):
    """Run `fn` with ops captured into a fresh child block. Returns
    (block, output Variables)."""
    program = default_main_program()
    blk = program.create_block()
    try:
        outs = fn(*args)
    finally:
        program.rollback()
    return blk, _as_list(outs)


def _block_external_reads(blocks: Sequence[Block],
                          extra_needed: Sequence[str] = ()) -> List[str]:
    """Names read by the blocks' ops but not produced inside them, plus
    any `extra_needed` names (e.g. branch OUTPUTS no op produces — an
    identity branch returns an outer var directly) — all must be fed to
    the lowering's env. Reuses the executor's canonical dataflow walk."""
    from ..core.executor import _analyze_block

    reads: List[str] = []
    seen = set()
    produced = set()
    for blk in blocks:
        ext, writes = _analyze_block(blk)
        produced.update(writes)
        for n in ext:
            if n not in seen:
                seen.add(n)
                reads.append(n)
    for n in extra_needed:
        if n not in produced and n not in seen:
            seen.add(n)
            reads.append(n)
    return reads


def cond(pred: Variable, true_fn: Callable, false_fn: Optional[Callable] = None,
         name=None):
    """paddle.static.nn.cond — both branches trace into sub-blocks and must
    return the same structure of Variables (or both None)."""
    helper = LayerHelper("cond", name=name)
    true_blk, true_outs = _trace_sub_block(true_fn)
    false_blk, false_outs = _trace_sub_block(false_fn) if false_fn else (None, [])
    if len(true_outs) != len(false_outs):
        # includes false_fn=None with a value-returning true_fn — lax.cond
        # requires identical branch output structures
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"(true: {len(true_outs)}, false: {len(false_outs)}"
            f"{'; provide a false_fn' if false_fn is None else ''})")
    ext = _block_external_reads(
        [b for b in (true_blk, false_blk) if b],
        extra_needed=[v.name for v in true_outs + false_outs])
    ext = [n for n in ext if n != pred.name]
    out_vars = [helper.create_variable_for_type_inference(
        v.dtype if hasattr(v, "dtype") else "float32")
        for v in (true_outs or [])]
    helper.append_op(
        "cond", {"Cond": [pred], "X": ext},
        {"Out": [v.name for v in out_vars]},
        {"true_block": true_blk, "false_block": false_blk,
         "input_names": list(ext), "cond_name": pred.name,
         "true_out_names": [v.name for v in true_outs],
         "false_out_names": [v.name for v in false_outs]})
    if not out_vars:
        return None
    return out_vars[0] if len(out_vars) == 1 else out_vars


def while_loop(cond_fn: Callable, body_fn: Callable,
               loop_vars: Sequence[Variable], name=None,
               grad_max_iters: int = 0):
    """paddle.static.nn.while_loop — dynamic trip count via
    lax.while_loop.

    grad_max_iters=N makes the loop reverse-differentiable (the
    reference while_op's sub-block grad capability,
    controlflow/while_op.cc): the lowering becomes a bounded N-step
    scan whose carry freezes once the condition turns false, so
    backward flows through exactly the iterations that ran. Without
    it the loop is forward-only (XLA while has no transpose)."""
    helper = LayerHelper("while_loop", name=name)
    loop_vars = _as_list(loop_vars)
    cond_blk, cond_outs = _trace_sub_block(cond_fn, loop_vars)
    if len(cond_outs) != 1:
        raise ValueError("while_loop cond_fn must return one boolean")
    body_blk, body_outs = _trace_sub_block(body_fn, loop_vars)
    if len(body_outs) != len(loop_vars):
        raise ValueError(
            f"body_fn must return as many values as loop_vars "
            f"({len(body_outs)} vs {len(loop_vars)})")
    carry_names = [v.name for v in loop_vars]
    ext = [n for n in _block_external_reads(
        [cond_blk, body_blk],
        extra_needed=[v.name for v in cond_outs + body_outs])
        if n not in carry_names]
    out_vars = [helper.create_variable_for_type_inference(v.dtype)
                for v in loop_vars]
    helper.append_op(
        "while_loop", {"X": [v.name for v in loop_vars], "Ext": ext},
        {"Out": [v.name for v in out_vars]},
        {"cond_block": cond_blk, "body_block": body_blk,
         "carry_names": carry_names,
         "cond_out_name": cond_outs[0].name,
         "body_out_names": [v.name for v in body_outs],
         "ext_names": list(ext),
         "grad_max_iters": int(grad_max_iters)})
    return out_vars


def static_loop(n: int, body_fn: Callable, loop_vars: Sequence[Variable],
                name=None):
    """Fixed-trip-count loop via lax.scan — reverse-differentiable (the
    StaticRNN role). body_fn(i_var, *loop_vars) -> new loop_vars."""
    helper = LayerHelper("static_loop", name=name)
    loop_vars = _as_list(loop_vars)
    program = default_main_program()
    blk = program.create_block()
    try:
        i_var = blk.create_var(name=helper.name + ".i", shape=[],
                               dtype="int32", stop_gradient=True)
        body_outs = _as_list(body_fn(i_var, *loop_vars))
    finally:
        program.rollback()
    if len(body_outs) != len(loop_vars):
        raise ValueError("body_fn must return as many values as loop_vars")
    carry_names = [v.name for v in loop_vars]
    ext = [n for n in _block_external_reads(
        [blk], extra_needed=[v.name for v in body_outs])
        if n not in carry_names and n != i_var.name]
    out_vars = [helper.create_variable_for_type_inference(v.dtype)
                for v in loop_vars]
    helper.append_op(
        "static_loop", {"X": [v.name for v in loop_vars], "Ext": ext},
        {"Out": [v.name for v in out_vars]},
        {"body_block": blk, "carry_names": carry_names,
         "i_name": i_var.name, "num_steps": int(n),
         "body_out_names": [v.name for v in body_outs],
         "ext_names": list(ext)})
    return out_vars


class DynamicRNN:
    """Variable-length RNN builder (reference: layers/control_flow.py
    DynamicRNN — LoD-driven decode loops over lod_rank_table). Padded
    -dense redesign: sequences stay [B, S, D] with a Length tensor; the
    loop is ONE differentiable static_loop (lax.scan) over S steps whose
    memories FREEZE once a row passes its length (`where(i < len, new,
    old)`) — bit-equal final states to the reference's shrinking-batch
    schedule, compiler-friendly static shapes instead of LoD
    bookkeeping. The reference's array read/write ops back the per-step
    access (ops/control_flow_ops.py array_read/array_write).

    Usage (fluid surface):
        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(emb, length=seq_len)   # [B, D] per step
            prev = drnn.memory(shape=[H])
            h = some_layers(w, prev)
            drnn.update_memory(prev, h)
            drnn.output(h)
        out = drnn()                 # [B, S, H], zero past each length
    """

    def __init__(self, name=None):
        from ..core import unique_name

        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._uname = unique_name.generate(name or "drnn")
        self._program = default_main_program()
        self._step_inputs = []     # (stacked outer [S,B,D], step var)
        self._memories = []        # dict per memory
        self._outputs = []         # (outer zero buffer, inblock buf name,
        #                             step value var, out name)
        self._length = None
        self._max_len = None
        self._blk = None
        self._i = None
        self._results = None

    # -- inside-block API ---------------------------------------------------
    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            self._blk = self._program.create_block()
            self._i = self._blk.create_var(
                name=f"{self._uname}.i", shape=[], dtype="int32",
                stop_gradient=True)
            try:
                yield
            except BaseException:
                # assembling a half-built block would mask the user's
                # error with an unrelated secondary failure
                self._program.rollback()
                raise
            else:
                self._program.rollback()
                self._assemble()

        return cm()

    def _parent_block(self):
        return self._program.blocks[self._blk.parent_idx]

    def step_input(self, x: Variable, length: Optional[Variable] = None):
        """Declare a [B, S, D...] sequence input; returns its [B, D...]
        slice for the current step."""
        assert self._blk is not None, "call inside drnn.block()"
        if length is not None:
            self._length = length
        if self._max_len is None:
            if x.shape[1] is None or int(x.shape[1]) <= 0:
                raise ValueError(
                    f"DynamicRNN.step_input: the sequence dim of "
                    f"{x.name} is dynamic ({x.shape}) — the padded loop "
                    f"needs a static max length (reshape/pad the input)")
            self._max_len = int(x.shape[1])
        parent = self._parent_block()
        perm = [1, 0] + list(range(2, len(x.shape)))
        stacked = parent.create_var(
            name=f"{x.name}.{self._uname}.steps",
            shape=[x.shape[1], x.shape[0]] + list(x.shape[2:]),
            dtype=x.dtype, stop_gradient=bool(x.stop_gradient))
        parent.append_op("transpose2", {"X": [x.name]},
                         {"Out": [stacked.name]}, {"axis": perm})
        step = self._blk.create_var(
            name=f"{stacked.name}.t", shape=[x.shape[0]] + list(x.shape[2:]),
            dtype=x.dtype, stop_gradient=bool(x.stop_gradient))
        self._blk.append_op("array_read", {"X": [stacked.name],
                                           "I": [self._i.name]},
                            {"Out": [step.name]}, {})
        self._step_inputs.append((stacked, step))
        return step

    def static_input(self, x: Variable):
        """A non-sequence input visible every step (ext capture)."""
        return x

    def memory(self, init: Optional[Variable] = None, shape=None,
               value: float = 0.0, dtype="float32"):
        assert self._blk is not None, "call inside drnn.block()"
        parent = self._parent_block()
        if init is None:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            if not self._step_inputs:
                raise ValueError("declare a step_input before a "
                                 "shape-initialised memory (batch size)")
            stacked = self._step_inputs[0][0]
            b = stacked.shape[1]
            init = parent.create_var(
                name=f"{self._uname}.mem{len(self._memories)}.init",
                shape=[b] + list(shape), dtype=dtype, stop_gradient=True)
            # batch dim may be dynamic (-1): copy it from the stacked
            # input at run time (reference fill_constant_batch_size_like)
            parent.append_op(
                "fill_constant_batch_size_like",
                {"Input": [stacked.name]}, {"Out": [init.name]},
                {"shape": [1] + list(shape), "value": float(value),
                 "dtype": dtype, "input_dim_idx": 1,
                 "output_dim_idx": 0})
        mem = self._blk.create_var(
            name=f"{self._uname}.mem{len(self._memories)}",
            shape=list(init.shape), dtype=init.dtype)
        self._memories.append({"init": init, "mem": mem, "update": None})
        return mem

    def update_memory(self, mem: Variable, new: Variable):
        assert self._blk is not None, "call inside drnn.block()"
        rec = next(r for r in self._memories if r["mem"] is mem)
        if self._length is not None:
            new = self._masked(new, mem)
        rec["update"] = new

    def _masked(self, new: Variable, old: Variable):
        """where(i < length, new, old) — freeze finished rows."""
        blk = self._blk
        self._mask_n = getattr(self, "_mask_n", 0) + 1
        n = self._mask_n
        cond = blk.create_var(name=f"{self._uname}.live{n}",
                              shape=[old.shape[0]], dtype="bool",
                              stop_gradient=True)
        blk.append_op("less_than",
                      {"X": [self._i.name], "Y": [self._length.name]},
                      {"Out": [cond.name]}, {})
        for _ in range(max(len(old.shape) - 1, 0)):
            c2 = blk.create_var(name=f"{cond.name}.u",
                                shape=list(cond.shape) + [1],
                                dtype="bool", stop_gradient=True)
            blk.append_op("unsqueeze2", {"X": [cond.name]},
                          {"Out": [c2.name]},
                          {"axes": [len(cond.shape)]}, infer_shape=False)
            cond = c2
        out = blk.create_var(name=f"{new.name}.sel{n}",
                             shape=list(old.shape), dtype=old.dtype)
        blk.append_op("where", {"Condition": [cond.name], "X": [new.name],
                                "Y": [old.name]}, {"Out": [out.name]}, {})
        return out

    def output(self, *outs):
        assert self._blk is not None, "call inside drnn.block()"
        parent = self._parent_block()
        for o in outs:
            s = self._max_len
            buf_init = parent.create_var(
                name=f"{self._uname}.out{len(self._outputs)}.buf",
                shape=[s] + list(o.shape), dtype=o.dtype,
                stop_gradient=True)
            if self._step_inputs and any(d in (-1, None)
                                         for d in o.shape or ()):
                parent.append_op(
                    "fill_constant_batch_size_like",
                    {"Input": [self._step_inputs[0][0].name]},
                    {"Out": [buf_init.name]},
                    {"shape": [s, 1] + list(o.shape[1:]), "value": 0.0,
                     "dtype": str(o.dtype), "input_dim_idx": 1,
                     "output_dim_idx": 1})
            else:
                parent.append_op(
                    "fill_constant", {}, {"Out": [buf_init.name]},
                    {"shape": [s] + list(o.shape), "value": 0.0,
                     "dtype": str(o.dtype)})
            buf = self._blk.create_var(
                name=f"{buf_init.name}.c", shape=list(buf_init.shape),
                dtype=o.dtype)
            if self._length is not None:
                zero = self._blk.create_var(
                    name=f"{o.name}.z{len(self._outputs)}",
                    shape=list(o.shape), dtype=o.dtype,
                    stop_gradient=True)
                self._blk.append_op(
                    "fill_constant_batch_size_like",
                    {"Input": [o.name]}, {"Out": [zero.name]},
                    {"shape": [1] + list(o.shape[1:]), "value": 0.0,
                     "dtype": str(o.dtype), "input_dim_idx": 0,
                     "output_dim_idx": 0})
                o = self._masked(o, zero)
            new_buf = self._blk.create_var(
                name=f"{buf.name}.w", shape=list(buf.shape), dtype=o.dtype)
            self._blk.append_op("array_write",
                                {"X": [buf.name], "I": [self._i.name],
                                 "V": [o.name]},
                                {"Out": [new_buf.name]}, {})
            self._outputs.append({"init": buf_init, "buf": buf,
                                  "new_buf": new_buf})

    # -- assembly -----------------------------------------------------------
    def _assemble(self):
        blk = self._blk
        carries = [r["mem"] for r in self._memories] \
            + [r["buf"] for r in self._outputs]
        inits = [r["init"] for r in self._memories] \
            + [r["init"] for r in self._outputs]
        body_outs = []
        for r in self._memories:
            body_outs.append(r["update"] if r["update"] is not None
                             else r["mem"])
        body_outs += [r["new_buf"] for r in self._outputs]
        ext = [n for n in _block_external_reads(
            [blk], extra_needed=[v.name for v in body_outs])
            if n not in {c.name for c in carries} and n != self._i.name]
        out_vars = [self.helper.create_variable_for_type_inference(v.dtype)
                    for v in inits]
        self.helper.append_op(
            "static_loop", {"X": [v.name for v in inits], "Ext": ext},
            {"Out": [v.name for v in out_vars]},
            {"body_block": blk, "carry_names": [c.name for c in carries],
             "i_name": self._i.name, "num_steps": int(self._max_len),
             "body_out_names": [v.name for v in body_outs],
             "ext_names": list(ext)})
        n_mem = len(self._memories)
        finals = []
        for k, bufv in enumerate(out_vars[n_mem:]):
            # [S, B, D...] -> [B, S, D...] (rank from the init buffer —
            # static_loop outputs skip shape inference)
            rank = len(self._outputs[k]["init"].shape)
            out = self.helper.create_variable_for_type_inference(bufv.dtype)
            self.helper.append_op("transpose2", {"X": [bufv.name]},
                                  {"Out": [out.name]},
                                  {"axis": [1, 0] + list(range(2, rank))})
            finals.append(out)
        self._results = {"memories": out_vars[:n_mem], "outputs": finals}

    def __call__(self):
        assert self._results is not None, "finish drnn.block() first"
        outs = self._results["outputs"]
        return outs[0] if len(outs) == 1 else outs

    def final_memories(self):
        """Final (length-frozen) memory states — the reference's
        drnn memory at sequence end."""
        return self._results["memories"]


def lod_rank_table(x, level=0, name=None):
    """Build the length-descending rank table (reference:
    layers/control_flow.py lod_rank_table over lod_rank_table_op.cc).
    Padded form: x is the per-row Length tensor [B]; returns the
    (Items, Index) pair consumed by lod_tensor_to_array /
    array_to_lod_tensor / shrink_memory."""
    helper = LayerHelper("lod_rank_table", name=name)
    items = helper.create_variable_for_type_inference("int32", True)
    index = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("lod_rank_table", {"X": [x]},
                     {"Items": [items], "Index": [index]}, {})
    return items, index


def lod_tensor_to_array(x, table, name=None):
    """reference: layers/control_flow.py lod_tensor_to_array
    (lod_tensor_to_array_op.cc). `table` is the (Items, Index) pair from
    lod_rank_table; returns the [S, B, ...] step-stacked array with
    finished rows zeroed."""
    helper = LayerHelper("lod_tensor_to_array", name=name)
    items, index = table
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("lod_tensor_to_array",
                     {"X": [x], "RankTable": [items, index]},
                     {"Out": [out]}, {})
    return out


def array_to_lod_tensor(x, table, name=None):
    """reference: layers/control_flow.py array_to_lod_tensor — inverse of
    lod_tensor_to_array."""
    helper = LayerHelper("array_to_lod_tensor", name=name)
    items, index = table
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("array_to_lod_tensor",
                     {"X": [x], "RankTable": [items, index]},
                     {"Out": [out]}, {})
    return out


def shrink_memory(x, i, table, name=None):
    """reference: layers/control_flow.py shrink_memory
    (shrink_rnn_memory_op.cc) — zero the rank-ordered memory rows whose
    sequence finished before step i (static-shape form of the
    shrinking-batch decode)."""
    helper = LayerHelper("shrink_memory", name=name)
    items, index = table
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("shrink_rnn_memory",
                     {"X": [x], "RankTable": [items, index], "I": [i]},
                     {"Out": [out]}, {})
    return out


class IfElse:
    """Row-wise two-branch control flow over split/merge_lod_tensor
    (reference: python/paddle/fluid/layers/control_flow.py IfElse, built
    on split_lod_tensor_op.cc / merge_lod_tensor_op.cc).

    cond is a [B,1] boolean tensor. `ie.input(x)` inside a branch block
    returns that branch's row subset of x; `ie.output(...)` registers
    branch results; calling `ie()` merges true/false outputs row-wise.

    TPU re-design note: the reference COMPACTS each branch's rows; here
    both branch tensors keep the full [B, ...] shape with the other
    branch's rows zeroed (split_lod_tensor docstring) — merge picks
    row-wise, so results match the reference for row-local branch
    bodies. Branch code that mixes rows (batch norms/reductions) would
    see the zero rows; use layers.cond for whole-batch branching.

    ::

        ie = layers.IfElse(mask)
        with ie.true_block():
            ie.output(ie.input(x) * 2.0)
        with ie.false_block():
            ie.output(ie.input(x) - 1.0)
        out, = ie()
    """

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper("if_else", name=name)
        self._in_true = None
        self._true_outs = []
        self._false_outs = []
        self._splits = {}

    def _block(self, branch):
        import contextlib

        @contextlib.contextmanager
        def guard():
            if self._in_true is not None:
                raise RuntimeError("IfElse blocks cannot nest")
            self._in_true = branch
            try:
                yield
            finally:
                self._in_true = None

        return guard()

    def true_block(self):
        return self._block(True)

    def false_block(self):
        return self._block(False)

    def input(self, x):
        if self._in_true is None:
            raise RuntimeError("IfElse.input() must run inside "
                               "true_block()/false_block()")
        if x.name not in self._splits:
            t = self.helper.create_variable_for_type_inference(x.dtype)
            f = self.helper.create_variable_for_type_inference(x.dtype)
            self.helper.append_op(
                "split_lod_tensor", {"X": [x], "Mask": [self.cond]},
                {"OutTrue": [t], "OutFalse": [f]}, {})
            self._splits[x.name] = (t, f)
        t, f = self._splits[x.name]
        return t if self._in_true else f

    def output(self, *outs):
        if self._in_true is None:
            raise RuntimeError("IfElse.output() must run inside "
                               "true_block()/false_block()")
        (self._true_outs if self._in_true else self._false_outs).extend(outs)

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError(
                f"IfElse branches registered different output counts "
                f"(true {len(self._true_outs)}, false "
                f"{len(self._false_outs)})")
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            out = self.helper.create_variable_for_type_inference(t.dtype)
            self.helper.append_op(
                "merge_lod_tensor",
                {"InTrue": [t], "InFalse": [f], "Mask": [self.cond]},
                {"Out": [out]}, {})
            merged.append(out)
        return merged
