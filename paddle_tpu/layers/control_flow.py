"""Control-flow layer surface: cond / while_loop / static_loop.

Capability mirror of python/paddle/fluid/layers/control_flow.py (cond,
While/while_loop, StaticRNN) over the sub-block ops in
ops/control_flow_ops.py. Branch/body functions are traced into child
Blocks of the current program (the reference's sub-block mechanism,
conditional_block_op.cc / while_op.cc) and lowered to lax.cond /
lax.while_loop / lax.scan.

Differentiability: `cond` and `static_loop` differentiate through the
generic vjp grad maker (lax.cond/scan support reverse AD);
`while_loop` does NOT (lax.while_loop is forward-only in XLA) — use
static_loop when the trip count is static and gradients are needed,
mirroring the reference's StaticRNN-vs-While split.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

from ..core.ir import Block, Variable, default_main_program

from ..layer_helper import LayerHelper


def _as_list(v):
    if v is None:
        return []
    return list(v) if isinstance(v, (list, tuple)) else [v]


def _trace_sub_block(fn, args=()):
    """Run `fn` with ops captured into a fresh child block. Returns
    (block, output Variables)."""
    program = default_main_program()
    blk = program.create_block()
    try:
        outs = fn(*args)
    finally:
        program.rollback()
    return blk, _as_list(outs)


def _block_external_reads(blocks: Sequence[Block],
                          extra_needed: Sequence[str] = ()) -> List[str]:
    """Names read by the blocks' ops but not produced inside them, plus
    any `extra_needed` names (e.g. branch OUTPUTS no op produces — an
    identity branch returns an outer var directly) — all must be fed to
    the lowering's env. Reuses the executor's canonical dataflow walk."""
    from ..core.executor import _analyze_block

    reads: List[str] = []
    seen = set()
    produced = set()
    for blk in blocks:
        ext, writes = _analyze_block(blk)
        produced.update(writes)
        for n in ext:
            if n not in seen:
                seen.add(n)
                reads.append(n)
    for n in extra_needed:
        if n not in produced and n not in seen:
            seen.add(n)
            reads.append(n)
    return reads


def cond(pred: Variable, true_fn: Callable, false_fn: Optional[Callable] = None,
         name=None):
    """paddle.static.nn.cond — both branches trace into sub-blocks and must
    return the same structure of Variables (or both None)."""
    helper = LayerHelper("cond", name=name)
    true_blk, true_outs = _trace_sub_block(true_fn)
    false_blk, false_outs = _trace_sub_block(false_fn) if false_fn else (None, [])
    if len(true_outs) != len(false_outs):
        # includes false_fn=None with a value-returning true_fn — lax.cond
        # requires identical branch output structures
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"(true: {len(true_outs)}, false: {len(false_outs)}"
            f"{'; provide a false_fn' if false_fn is None else ''})")
    ext = _block_external_reads(
        [b for b in (true_blk, false_blk) if b],
        extra_needed=[v.name for v in true_outs + false_outs])
    ext = [n for n in ext if n != pred.name]
    out_vars = [helper.create_variable_for_type_inference(
        v.dtype if hasattr(v, "dtype") else "float32")
        for v in (true_outs or [])]
    helper.append_op(
        "cond", {"Cond": [pred], "X": ext},
        {"Out": [v.name for v in out_vars]},
        {"true_block": true_blk, "false_block": false_blk,
         "input_names": list(ext), "cond_name": pred.name,
         "true_out_names": [v.name for v in true_outs],
         "false_out_names": [v.name for v in false_outs]})
    if not out_vars:
        return None
    return out_vars[0] if len(out_vars) == 1 else out_vars


def while_loop(cond_fn: Callable, body_fn: Callable,
               loop_vars: Sequence[Variable], name=None,
               grad_max_iters: int = 0):
    """paddle.static.nn.while_loop — dynamic trip count via
    lax.while_loop.

    grad_max_iters=N makes the loop reverse-differentiable (the
    reference while_op's sub-block grad capability,
    controlflow/while_op.cc): the lowering becomes a bounded N-step
    scan whose carry freezes once the condition turns false, so
    backward flows through exactly the iterations that ran. Without
    it the loop is forward-only (XLA while has no transpose)."""
    helper = LayerHelper("while_loop", name=name)
    loop_vars = _as_list(loop_vars)
    cond_blk, cond_outs = _trace_sub_block(cond_fn, loop_vars)
    if len(cond_outs) != 1:
        raise ValueError("while_loop cond_fn must return one boolean")
    body_blk, body_outs = _trace_sub_block(body_fn, loop_vars)
    if len(body_outs) != len(loop_vars):
        raise ValueError(
            f"body_fn must return as many values as loop_vars "
            f"({len(body_outs)} vs {len(loop_vars)})")
    carry_names = [v.name for v in loop_vars]
    ext = [n for n in _block_external_reads(
        [cond_blk, body_blk],
        extra_needed=[v.name for v in cond_outs + body_outs])
        if n not in carry_names]
    out_vars = [helper.create_variable_for_type_inference(v.dtype)
                for v in loop_vars]
    helper.append_op(
        "while_loop", {"X": [v.name for v in loop_vars], "Ext": ext},
        {"Out": [v.name for v in out_vars]},
        {"cond_block": cond_blk, "body_block": body_blk,
         "carry_names": carry_names,
         "cond_out_name": cond_outs[0].name,
         "body_out_names": [v.name for v in body_outs],
         "ext_names": list(ext),
         "grad_max_iters": int(grad_max_iters)})
    return out_vars


def static_loop(n: int, body_fn: Callable, loop_vars: Sequence[Variable],
                name=None):
    """Fixed-trip-count loop via lax.scan — reverse-differentiable (the
    StaticRNN role). body_fn(i_var, *loop_vars) -> new loop_vars."""
    helper = LayerHelper("static_loop", name=name)
    loop_vars = _as_list(loop_vars)
    program = default_main_program()
    blk = program.create_block()
    try:
        i_var = blk.create_var(name=helper.name + ".i", shape=[],
                               dtype="int32", stop_gradient=True)
        body_outs = _as_list(body_fn(i_var, *loop_vars))
    finally:
        program.rollback()
    if len(body_outs) != len(loop_vars):
        raise ValueError("body_fn must return as many values as loop_vars")
    carry_names = [v.name for v in loop_vars]
    ext = [n for n in _block_external_reads(
        [blk], extra_needed=[v.name for v in body_outs])
        if n not in carry_names and n != i_var.name]
    out_vars = [helper.create_variable_for_type_inference(v.dtype)
                for v in loop_vars]
    helper.append_op(
        "static_loop", {"X": [v.name for v in loop_vars], "Ext": ext},
        {"Out": [v.name for v in out_vars]},
        {"body_block": blk, "carry_names": carry_names,
         "i_name": i_var.name, "num_steps": int(n),
         "body_out_names": [v.name for v in body_outs],
         "ext_names": list(ext)})
    return out_vars
