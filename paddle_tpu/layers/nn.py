"""Op-emitting layer functions — the fluid `layers.*` surface.

Capability mirror of python/paddle/fluid/layers/nn.py (fc, conv2d,
batch_norm, layer_norm, dropout, embedding, …, 156 functions),
layers/tensor.py and layers/loss.py. Each function creates output vars and
appends ops; nothing executes until an Executor runs the program.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core import unique_name
from ..core.ir import Variable, default_main_program
from ..core.types import convert_dtype
from ..initializer import Constant, Xavier
from ..layer_helper import LayerHelper
from ..parallel.api import set_logical_axes
from ..param_attr import ParamAttr


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


def data(name: str, shape: Sequence[int], dtype="float32",
         append_batch_size: bool = True, lod_level: int = 0,
         stop_gradient: bool = True) -> Variable:
    """reference: fluid/layers/io.py data() — placeholder fed at run time."""
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    block = default_main_program().global_block()
    var = block.create_var(name=name, shape=shape, dtype=dtype,
                           stop_gradient=stop_gradient, lod_level=lod_level)
    return var


def static_data(name: str, shape: Sequence[int], dtype="float32",
                lod_level: int = 0) -> Variable:
    """paddle.static.data — shape given in full (may contain -1)."""
    return data(name, shape, dtype, append_batch_size=False, lod_level=lod_level)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False, name=None):
    helper = LayerHelper("global_var", name=name)
    block = helper.main_program.global_block()
    var = block.create_var(name=name or unique_name.generate("global_var"),
                           shape=shape, dtype=dtype, persistable=persistable,
                           stop_gradient=True)
    helper.startup_program.global_block().create_var(
        name=var.name, shape=shape, dtype=dtype, persistable=persistable,
        stop_gradient=True)
    helper.startup_program.global_block().append_op(
        "fill_constant", {}, {"Out": [var.name]},
        {"shape": list(shape), "value": float(value),
         "dtype": str(np.dtype(convert_dtype(dtype)))})
    return var


# -- dense / conv layers ------------------------------------------------------

def fc(input: Variable, size: int, num_flatten_dims: int = 1, param_attr=None,
       bias_attr=None, act: Optional[str] = None, name=None) -> Variable:
    """reference: layers/nn.py fc() — mul(+flatten) → elementwise_add → act."""
    helper = LayerHelper("fc", name=name)
    in_features = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_features, size], input.dtype)
    # logical axis names: the rule table (parallel/axis_rules.py) maps
    # these to mesh axes at compile time (explicit shard_tensor wins)
    set_logical_axes(w, ("embed", "mlp"))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("mul", {"X": [input], "Y": [w]}, {"Out": [out]},
                     {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [size], input.dtype, is_bias=True)
        set_logical_axes(b, ("mlp",))
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [pre_act]}, {"axis": num_flatten_dims})
        out = pre_act
    return helper.append_activation(out, act)


def linear(x: Variable, weight: Variable, bias: Optional[Variable] = None,
           name=None) -> Variable:
    helper = LayerHelper("linear", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul_v2", {"X": [x], "Y": [weight]}, {"Out": [out]}, {})
    if bias is not None:
        out2 = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [bias]},
                         {"Out": [out2]}, {"axis": -1})
        out = out2
    return out


def embedding(input: Variable, size, is_sparse: bool = False,
              padding_idx: Optional[int] = None, param_attr=None,
              dtype="float32", name=None) -> Variable:
    """reference: layers/nn.py embedding() (lookup_table_op.cc)."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, list(size), dtype,
                                default_initializer=Xavier())
    set_logical_axes(w, ("vocab", "embed"))
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("lookup_table_v2", {"W": [w], "Ids": [input]},
                     {"Out": [out]},
                     {"padding_idx": -1 if padding_idx is None else padding_idx,
                      "is_sparse": is_sparse})
    return out


def conv2d(input: Variable, num_filters: int, filter_size, stride=1, padding=0,
           dilation=1, groups: int = 1, param_attr=None, bias_attr=None,
           act: Optional[str] = None, use_cudnn: bool = True, name=None,
           data_format: str = "NCHW") -> Variable:
    """reference: layers/nn.py conv2d() (conv_op.cc). use_cudnn kept for API
    parity; XLA owns the conv algorithm on TPU."""
    helper = LayerHelper("conv2d", name=name)
    c_in = input.shape[1]
    fsize = _pair(filter_size)
    w_shape = [num_filters, c_in // groups, fsize[0], fsize[1]]
    from ..initializer import MSRA

    w = helper.create_parameter(param_attr, w_shape, input.dtype,
                                default_initializer=MSRA(uniform=False))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv2d", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation), "groups": groups,
                      "data_format": data_format})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                    is_bias=True)
        pre = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [pre]}, {"axis": 1})
        out = pre
    return helper.append_activation(out, act)


def conv2d_transpose(input: Variable, num_filters: int, filter_size, stride=1,
                     padding=0, dilation=1, groups: int = 1, param_attr=None,
                     bias_attr=None, act=None, name=None) -> Variable:
    helper = LayerHelper("conv2d_transpose", name=name)
    c_in = input.shape[1]
    fsize = _pair(filter_size)
    w = helper.create_parameter(param_attr,
                                [c_in, num_filters // groups, fsize[0], fsize[1]],
                                input.dtype, default_initializer=Xavier())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv2d_transpose", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation), "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype, is_bias=True)
        pre = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [pre]}, {"axis": 1})
        out = pre
    return helper.append_activation(out, act)


def pool2d(input: Variable, pool_size=2, pool_type: str = "max", pool_stride=None,
           pool_padding=0, global_pooling: bool = False, ceil_mode: bool = False,
           exclusive: bool = True, name=None) -> Variable:
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", {"X": [input]}, {"Out": [out]},
                     {"ksize": _pair(pool_size), "pooling_type": pool_type,
                      "strides": _pair(pool_stride or pool_size),
                      "paddings": _pair(pool_padding),
                      "global_pooling": global_pooling, "ceil_mode": ceil_mode,
                      "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="avg", name=None):
    """reference: layers/nn.py adaptive_pool2d — pool_size is the OUTPUT
    size; the pool2d op implements the reference floor/ceil cell bounds
    for any output (1x1 lowers to a global reduction)."""
    size = tuple(_pair(pool_size))
    if size == (1, 1):
        return pool2d(input, pool_type=pool_type, global_pooling=True,
                      name=name)
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", {"X": [input]}, {"Out": [out]},
                     {"ksize": list(size), "pooling_type": pool_type,
                      "adaptive": True})
    return out


def batch_norm(input: Variable, act: Optional[str] = None, is_test: bool = False,
               momentum: float = 0.9, epsilon: float = 1e-5, param_attr=None,
               bias_attr=None, data_layout: str = "NCHW", name=None,
               moving_mean_name=None, moving_variance_name=None,
               use_global_stats: bool = False) -> Variable:
    """reference: layers/nn.py batch_norm() (batch_norm_op.cc)."""
    helper = LayerHelper("batch_norm", name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(param_attr, [c], "float32",
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, [c], "float32", is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, initializer=Constant(0.0),
                  trainable=False), [c], "float32")
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, initializer=Constant(1.0),
                  trainable=False), [c], "float32")
    mean.stop_gradient = True
    variance.stop_gradient = True
    y = helper.create_variable_for_type_inference(input.dtype)
    saved_mean = helper.create_variable_for_type_inference("float32", True)
    saved_var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        "batch_norm",
        {"X": [input], "Scale": [scale], "Bias": [bias], "Mean": [mean],
         "Variance": [variance]},
        {"Y": [y], "MeanOut": [mean], "VarianceOut": [variance],
         "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout, "use_global_stats": use_global_stats})
    return helper.append_activation(y, act)


def layer_norm(input: Variable, scale: bool = True, shift: bool = True,
               begin_norm_axis: int = 1, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, act=None, name=None) -> Variable:
    helper = LayerHelper("layer_norm", name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, norm_shape, "float32",
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, norm_shape, "float32", is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("layer_norm", inputs,
                     {"Y": [y], "Mean": [mean], "Variance": [var]},
                     {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(y, act)


def dropout(x: Variable, dropout_prob: float, is_test: bool = False,
            seed: Optional[int] = None,
            dropout_implementation: str = "downgrade_in_infer",
            name=None) -> Variable:
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", True)
    helper.append_op("dropout", {"X": [x]}, {"Out": [out], "Mask": [mask]},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "seed": seed or default_main_program().next_op_seed(),
                      "dropout_implementation": dropout_implementation})
    return out


# -- losses / metrics ---------------------------------------------------------

def cross_entropy(input: Variable, label: Variable, soft_label: bool = False,
                  ignore_index: int = -100) -> Variable:
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy", {"X": [input], "Label": [label]},
                     {"Y": [out]},
                     {"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits: Variable, label: Variable,
                               soft_label: bool = False, ignore_index: int = -100,
                               axis: int = -1,
                               return_softmax: bool = False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": [logits], "Label": [label]},
                     {"Softmax": [softmax_out], "Loss": [loss]},
                     {"soft_label": soft_label, "ignore_index": ignore_index,
                      "axis": axis})
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": [x], "Label": [label]}, {"Out": [out]}, {})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square_error_cost", {"Input": [input], "Label": [label]},
                     {"Out": [out]}, {})
    return out


def accuracy(input: Variable, label: Variable, k: int = 1) -> Variable:
    """reference: layers/metric_op.py accuracy() — topk + accuracy op."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(input.dtype, True)
    topk_idx = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("top_k", {"X": [input]},
                     {"Out": [topk_out], "Indices": [topk_idx]}, {"k": k})
    acc = helper.create_variable_for_type_inference("float32", True)
    correct = helper.create_variable_for_type_inference("int32", True)
    total = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("accuracy",
                     {"Out": [topk_out], "Indices": [topk_idx], "Label": [label]},
                     {"Accuracy": [acc], "Correct": [correct], "Total": [total]},
                     {})
    return acc


def topk(input: Variable, k: int = 1):
    helper = LayerHelper("top_k")
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int64")
    helper.append_op("top_k", {"X": [input]}, {"Out": [out], "Indices": [idx]},
                     {"k": k})
    return out, idx


def mean(x: Variable, name=None) -> Variable:
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", {"X": [x]}, {"Out": [out]}, {})
    return out


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    sq = reduce_sum(elementwise_mul(x, x), dim=[axis], keep_dim=True)
    norm = sqrt(elementwise_max(sq, fill_constant([1], x.dtype, epsilon)))
    return elementwise_div(x, norm)


# -- generic emitters ---------------------------------------------------------

def _unary(op_type):
    def fn(x: Variable, name=None) -> Variable:
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, {})
        return out

    fn.__name__ = op_type
    fn.__doc__ = f"Emit `{op_type}` op (see ops registry)."
    return fn


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
exp = _unary("exp")
log = _unary("log")
sqrt = _unary("sqrt")
rsqrt = _unary("rsqrt")
square = _unary("square")
abs = _unary("abs")
floor = _unary("floor")
ceil = _unary("ceil")
round = _unary("round")
reciprocal = _unary("reciprocal")
softplus = _unary("softplus")
softsign = _unary("softsign")
silu = _unary("silu")
swish = _unary("swish")
sin = _unary("sin")
cos = _unary("cos")
erf = _unary("erf")
sign = _unary("sign")
logsigmoid = _unary("logsigmoid")


def gelu(x: Variable, approximate: bool = False, name=None) -> Variable:
    helper = LayerHelper("gelu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("gelu", {"X": [x]}, {"Out": [out]},
                     {"approximate": approximate})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("leaky_relu", {"X": [x]}, {"Out": [out]}, {"alpha": alpha})
    return out


def softmax(input: Variable, axis: int = -1, use_cudnn: bool = False,
            name=None) -> Variable:
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", {"X": [input]}, {"Out": [out]}, {"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_softmax", {"X": [input]}, {"Out": [out]},
                     {"axis": axis})
    return out


def _to_var(block, value, ref: Variable) -> Variable:
    """Promote python/numpy scalar to a fill_constant var."""
    if isinstance(value, Variable):
        return value
    helper = LayerHelper("const")
    out = helper.create_variable_for_type_inference(ref.dtype, True)
    helper.append_op("fill_constant", {}, {"Out": [out]},
                     {"shape": [1], "value": float(value),
                      "dtype": str(np.dtype(ref.dtype))})
    return out


def _elementwise_binary(x, y, op_type, reverse=False):
    if not isinstance(x, Variable) and isinstance(y, Variable):
        x, y = y, x
        reverse = not reverse if op_type in ("elementwise_sub", "elementwise_div") else reverse
    helper = LayerHelper(op_type)
    y = _to_var(x.block, y, x)
    if reverse:
        x, y = y, x
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]}, {"axis": -1})
    return out


def _binary(op_type):
    def fn(x: Variable, y: Variable, axis: int = -1, act=None, name=None) -> Variable:
        helper = LayerHelper(op_type, name=name)
        y = _to_var(x.block, y, x)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]},
                         {"axis": axis})
        return helper.append_activation(out, act)

    fn.__name__ = op_type
    return fn


elementwise_add = _binary("elementwise_add")
elementwise_sub = _binary("elementwise_sub")
elementwise_mul = _binary("elementwise_mul")
elementwise_div = _binary("elementwise_div")
elementwise_pow = _binary("elementwise_pow")
elementwise_max = _binary("elementwise_max")
elementwise_min = _binary("elementwise_min")
elementwise_mod = _binary("elementwise_mod")


def _compare(x, y, op_type):
    helper = LayerHelper(op_type)
    y = _to_var(x.block, y, x)
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]}, {})
    return out


def equal(x, y, name=None):
    return _compare(x, y, "equal")


def not_equal(x, y, name=None):
    return _compare(x, y, "not_equal")


def less_than(x, y, name=None):
    return _compare(x, y, "less_than")


def greater_than(x, y, name=None):
    return _compare(x, y, "greater_than")


def _reduce_layer(op_type):
    def fn(input: Variable, dim=None, keep_dim: bool = False, name=None) -> Variable:
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is not None and not isinstance(dim, (list, tuple)):
            dim = [dim]
        helper.append_op(op_type, {"X": [input]}, {"Out": [out]},
                         {"dim": dim, "keep_dim": keep_dim,
                          "reduce_all": dim is None})
        return out

    fn.__name__ = op_type
    return fn


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def matmul(x: Variable, y: Variable, transpose_x: bool = False,
           transpose_y: bool = False, alpha: float = 1.0, name=None) -> Variable:
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                      "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def reshape(x: Variable, shape, actual_shape=None, inplace=False, name=None) -> Variable:
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("reshape2", {"X": [x]}, {"Out": [out], "XShape": [xshape]},
                     {"shape": list(shape)})
    return out


def transpose(x: Variable, perm, name=None) -> Variable:
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("transpose2", {"X": [x]},
                     {"Out": [out], "XShape": [xshape]}, {"axis": list(perm)})
    return out


def concat(input: List[Variable], axis: int = 0, name=None) -> Variable:
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", {"X": input}, {"Out": [out]}, {"axis": axis})
    return out


def split(input: Variable, num_or_sections, dim: int = -1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = None
    else:
        n = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op("split", {"X": [input]}, {"Out": outs},
                     {"axis": dim, "num": 0 if sections else n,
                      "sections": sections or []})
    return outs


def stack(x: List[Variable], axis: int = 0, name=None) -> Variable:
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", {"X": x}, {"Y": [out]}, {"axis": axis})
    return out


def squeeze(input: Variable, axes, name=None) -> Variable:
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("squeeze2", {"X": [input]},
                     {"Out": [out], "XShape": [xshape]}, {"axes": axes})
    return out


def unsqueeze(input: Variable, axes, name=None) -> Variable:
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("unsqueeze2", {"X": [input]},
                     {"Out": [out], "XShape": [xshape]}, {"axes": axes})
    return out


def flatten(x: Variable, axis: int = 1, name=None) -> Variable:
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("flatten2", {"X": [x]}, {"Out": [out], "XShape": [xshape]},
                     {"axis": axis})
    return out


def slice(input: Variable, axes, starts, ends, name=None) -> Variable:
    helper = LayerHelper("slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", {"Input": [input]}, {"Out": [out]},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends)})
    return out


def _getitem(var: Variable, idx):
    if not isinstance(idx, tuple):
        idx = (idx,)
    ndim = len(var.shape or ())
    # resolve Ellipsis: indices after it anchor to the trailing axes
    axis_of = []
    ell = next((k for k, s in enumerate(idx) if s is Ellipsis), None)
    for k in range(len(idx)):
        if ell is None or k < ell:
            axis_of.append(k)
        elif k == ell:
            axis_of.append(None)
        else:
            axis_of.append(ndim - (len(idx) - k))
    axes, starts, ends, decrease = [], [], [], []
    for k, s in enumerate(idx):
        i = axis_of[k]
        if s is Ellipsis:
            continue
        if isinstance(s, int):
            axes.append(i)
            starts.append(s)
            ends.append(s + 1 if s != -1 else np.iinfo(np.int32).max)
            decrease.append(i)
        elif isinstance(s, type(None)):
            raise NotImplementedError("newaxis indexing not supported yet")
        else:
            if s.start is None and s.stop is None:
                continue
            axes.append(i)
            starts.append(s.start or 0)
            ends.append(s.stop if s.stop is not None else np.iinfo(np.int32).max)
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(var.dtype)
    helper.append_op("slice", {"Input": [var]}, {"Out": [out]},
                     {"axes": axes, "starts": starts, "ends": ends,
                      "decrease_axis": decrease})
    return out


def gather(input: Variable, index: Variable, name=None) -> Variable:
    helper = LayerHelper("gather", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", {"X": [input], "Index": [index]},
                     {"Out": [out]}, {})
    return out


def one_hot(input: Variable, depth: int, name=None) -> Variable:
    helper = LayerHelper("one_hot", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", {"X": [input]}, {"Out": [out]},
                     {"depth": depth})
    return out


def cast(x: Variable, dtype) -> Variable:
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", {"X": [x]}, {"Out": [out]},
                     {"out_dtype": str(np.dtype(convert_dtype(dtype)))})
    return out


def scale(x: Variable, scale: float = 1.0, bias: float = 0.0,
          bias_after_scale: bool = True, act=None, name=None) -> Variable:
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", {"X": [x]}, {"Out": [out]},
                     {"scale": scale, "bias": bias,
                      "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def clip(x: Variable, min: float, max: float, name=None) -> Variable:
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", {"X": [x]}, {"Out": [out]},
                     {"min": min, "max": max})
    return out


def fill_constant(shape, dtype, value, name=None, out=None) -> Variable:
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("fill_constant", {}, {"Out": [out]},
                     {"shape": list(shape), "value": float(value),
                      "dtype": str(np.dtype(convert_dtype(dtype)))})
    return out


def zeros(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 0.0, name=name)


def ones(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 1.0, name=name)


def zeros_like(x, name=None):
    helper = LayerHelper("zeros_like", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", {"X": [x]}, {"Out": [out]}, {})
    return out


def ones_like(x, name=None):
    helper = LayerHelper("ones_like", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_any_like", {"X": [x]}, {"Out": [out]},
                     {"value": 1.0, "dtype": -1})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign_value", {}, {"Out": [output]},
                         {"shape": list(input.shape),
                          "values": input.flatten().tolist(),
                          "dtype": str(input.dtype)})
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("assign", {"X": [input]}, {"Out": [output]}, {})
    return output


def increment(x: Variable, value: float = 1.0, in_place: bool = True) -> Variable:
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", {"X": [x]}, {"Out": [out]}, {"step": value})
    return out


def expand(x: Variable, expand_times, name=None) -> Variable:
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", {"X": [x]}, {"Out": [out]},
                     {"expand_times": list(expand_times)})
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where", {"Condition": [condition], "X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


def argmax(x, axis=-1, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(label.dtype)
    helper.append_op("label_smooth", {"X": [label]}, {"Out": [out]},
                     {"epsilon": epsilon})
    return out


def dropout_with_impl(x, p, is_test=False):
    return dropout(x, p, is_test=is_test,
                   dropout_implementation="upscale_in_train")


def _attn_dropout_attrs(attrs, dropout_rate, is_test, seed):
    """Shared build-time attrs for attention-probs dropout (flash + ring)."""
    if dropout_rate and not is_test:
        attrs["dropout_prob"] = float(dropout_rate)
        attrs["seed"] = (default_main_program().next_op_seed()
                         if seed is None else int(seed))


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    dropout_rate=0.0, is_test=False, seed=None, name=None,
                    num_heads=None):
    """Fused attention: softmax(q k^T * scale + bias) v via the Pallas
    flash-attention kernel (ops/attention_ops.py). q [B,H,Sq,D];
    k,v [B,H,Sk,D] — or PACKED [B,S,n*hd] 3-D with num_heads set,
    feeding the projection outputs straight to the kernels with zero
    head transposes in the program; bias optional, broadcastable to
    [B,1,1,Sk]. dropout_rate>0 (and not is_test) applies attention-probs
    dropout with a per-step position-keyed mask (recomputed in the
    backward)."""
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    # saved log-sum-exp residual: lets the grad op run the bwd kernels
    # from the saved forward instead of re-executing the fwd kernel
    lse = helper.create_variable_for_type_inference("float32")
    lse.stop_gradient = True
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    attrs = {"causal": causal}
    if len(q.shape or ()) == 3:
        if not num_heads:
            raise ValueError("packed (3-D) flash_attention needs num_heads")
        attrs["num_heads"] = int(num_heads)
        # head_dim is the sharding-INVARIANT key: under tensor-parallel
        # sharding the lowering sees the LOCAL column count and derives
        # the local head count as htot_local // head_dim
        attrs["head_dim"] = int(q.shape[-1]) // int(num_heads)
    if scale is not None:
        attrs["scale"] = float(scale)
    _attn_dropout_attrs(attrs, dropout_rate, is_test, seed)
    helper.append_op("flash_attention", inputs,
                     {"Out": [out], "Lse": [lse]}, attrs)
    return out


def ring_attention(q, k, v, bias=None, causal=False, scale=None,
                   axis_name="sp", nranks=1, dropout_rate=0.0,
                   is_test=False, seed=None, name=None):
    """Sequence-parallel ring attention (parallel/ring_attention.py).
    q/k/v are sequence shards [B,H,S_local,D]; bias a key-bias shard
    [B,S_local] travelling with kv around the ring. dropout_rate applies
    the globally-position-keyed probs dropout (same mask as the unsharded
    paths — sp sharding does not change numerics)."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    attrs = {"causal": causal, "axis_name": axis_name, "nranks": nranks}
    if scale is not None:
        attrs["scale"] = float(scale)
    _attn_dropout_attrs(attrs, dropout_rate, is_test, seed)
    helper.append_op("ring_attention", inputs, {"Out": [out]}, attrs)
    return out


# -- RNN + sequence + metric layer surface (reference: layers/nn.py
# dynamic_lstm/dynamic_gru, sequence_* wrappers, layers/metric_op.py auc) ----

def lstm_unit_layer(input, hidden_size, param_attr=None, bias_attr=None,
                    h0=None, c0=None, is_reverse=False, seq_length=None,
                    name=None):
    """Dense padded LSTM over [B,S,D] (the reference's dynamic_lstm with
    LoD replaced by an optional seq_length mask — ops/rnn_ops.py)."""
    helper = LayerHelper("lstm", name=name)
    d = int(input.shape[-1])
    wx = helper.create_parameter(param_attr or ParamAttr(), [d, 4 * hidden_size],
                                 input.dtype)
    wh = helper.create_parameter(
        ParamAttr(name=unique_name.generate((name or "lstm") + "_wh")),
        [hidden_size, 4 * hidden_size], input.dtype)
    b = helper.create_parameter(bias_attr or ParamAttr(), [4 * hidden_size],
                                input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Input": [input], "WeightX": [wx], "WeightH": [wh], "Bias": [b]}
    if h0 is not None:
        inputs["H0"] = [h0]
    if c0 is not None:
        inputs["C0"] = [c0]
    if seq_length is not None:
        inputs["SequenceLength"] = [seq_length]
    helper.append_op("lstm", inputs,
                     {"Out": [out], "LastH": [last_h], "LastC": [last_c]},
                     {"is_reverse": is_reverse})
    return out, last_h, last_c


def gru_layer(input, hidden_size, param_attr=None, bias_attr=None, h0=None,
              is_reverse=False, seq_length=None, name=None):
    """Dense padded GRU over [B,S,D] (reference: dynamic_gru)."""
    helper = LayerHelper("gru", name=name)
    d = int(input.shape[-1])
    wx = helper.create_parameter(param_attr or ParamAttr(), [d, 3 * hidden_size],
                                 input.dtype)
    wh = helper.create_parameter(
        ParamAttr(name=unique_name.generate((name or "gru") + "_wh")),
        [hidden_size, 3 * hidden_size], input.dtype)
    b = helper.create_parameter(bias_attr or ParamAttr(), [3 * hidden_size],
                                input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Input": [input], "WeightX": [wx], "WeightH": [wh], "Bias": [b]}
    if h0 is not None:
        inputs["H0"] = [h0]
    if seq_length is not None:
        inputs["SequenceLength"] = [seq_length]
    helper.append_op("gru", inputs, {"Out": [out], "LastH": [last_h]},
                     {"is_reverse": is_reverse})
    return out, last_h


def sequence_mask(x, maxlen, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("sequence_mask", {"X": [x]}, {"Y": [out]},
                     {"maxlen": int(maxlen), "out_dtype": dtype})
    return out


def sequence_pool(input, pool_type="sum", lod=None, name=None):
    """Pool a (flat values, lod) pair per sequence; `lod` is the explicit
    offsets tensor the dataset layer yields for lod slots."""
    if lod is None:
        raise ValueError(
            "sequence_pool requires lod= (the explicit offsets tensor; LoD "
            "travels beside values on TPU — see ops/sequence_ops.py)")
    helper = LayerHelper("sequence_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("sequence_pool", {"X": [input], "Lod": [lod]},
                     {"Out": [out], "MaxIndex": [idx]},
                     {"pooltype": pool_type.upper()})
    return out


def distributed_embedding(ids, table_name, dim, endpoints, seed=0,
                          lr=0.01, name=None):
    """Embedding lookup against the multi-node sharded KV service
    (reference: layers emitting distributed_lookup_table_op for
    is_distributed tables). The table lives in pserver host memory — far
    larger than HBM; the backward pushes row grads for the server-side
    SGD apply. Creates the [1, dim] proxy parameter that threads the op
    into the grad graph (the real rows are remote)."""
    helper = LayerHelper("distributed_embedding", name=name)
    w = helper.create_parameter(
        ParamAttr(name=unique_name.generate(f"{table_name}_proxy")),
        [1, dim], "float32")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "distributed_lookup_table", {"Ids": [ids], "W": [w]},
        {"Out": [out]},
        {"endpoints": endpoints if isinstance(endpoints, str)
         else ",".join(endpoints),
         "table_name": table_name, "dim": int(dim), "seed": int(seed),
         "lr": float(lr)})
    return out


def linear_chain_crf(input, label, param_attr=None, length=None, name=None):
    """CRF NLL layer (reference: layers/nn.py linear_chain_crf): creates
    the [T+2, T] 'transition' parameter (rows 0/1 = start/stop weights)
    and returns the per-sequence negative log-likelihood [B, 1].
    input [B, S, T] emissions, label [B, S] int, length [B] optional."""
    helper = LayerHelper("linear_chain_crf", name=name)
    t = int(input.shape[-1])
    trans = helper.create_parameter(param_attr or ParamAttr(),
                                    [t + 2, t], "float32")
    ll = helper.create_variable_for_type_inference("float32")
    alpha = helper.create_variable_for_type_inference("float32", True)
    ee = helper.create_variable_for_type_inference("float32", True)
    te = helper.create_variable_for_type_inference("float32", True)
    ins = {"Emission": [input], "Transition": [trans], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("linear_chain_crf", ins,
                     {"LogLikelihood": [ll], "Alpha": [alpha],
                      "EmissionExps": [ee], "TransitionExps": [te]}, {})
    return ll


def crf_decoding(input, param_attr, label=None, length=None, name=None):
    """Viterbi decode under a trained CRF (reference: layers/nn.py
    crf_decoding). param_attr must name the SAME transition parameter the
    linear_chain_crf layer trained."""
    helper = LayerHelper("crf_decoding", name=name)
    t = int(input.shape[-1])
    trans = helper.create_parameter(param_attr, [t + 2, t], "float32")
    out = helper.create_variable_for_type_inference("int64", True)
    ins = {"Emission": [input], "Transition": [trans]}
    if label is not None:
        ins["Label"] = [label]
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("crf_decoding", ins, {"ViterbiPath": [out]}, {})
    return out


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             path_table=None, path_code=None, name=None):
    """Hierarchical sigmoid loss layer (reference: layers/nn.py hsigmoid
    → hierarchical_sigmoid_op.cc): O(log C) softmax over the default
    complete binary tree, or a custom tree via path_table/path_code.
    Returns Cost [B, 1]."""
    if (path_table is None) != (path_code is None):
        raise ValueError(
            "hsigmoid: path_table and path_code must be passed together "
            "(custom-tree mode) or both omitted (default complete tree)")
    helper = LayerHelper("hsigmoid", name=name)
    d = int(input.shape[-1])
    # reference shapes: default tree has num_classes-1 internal nodes;
    # a custom tree's node ids may reach num_classes-1, so its table is
    # [num_classes, d] (fluid layers/nn.py hsigmoid)
    rows = num_classes - 1 if path_table is None else num_classes
    w = helper.create_parameter(param_attr, [rows, d], input.dtype)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [rows], input.dtype,
                                    is_bias=True)
        ins["Bias"] = [b]
    if path_table is not None:
        ins["PathTable"] = [path_table]
        ins["PathCode"] = [path_code]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("hierarchical_sigmoid", ins,
                     {"Out": [out], "PreOut": [pre]},
                     {"num_classes": int(num_classes)})
    return out


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decode = argmax per step + ctc_align collapse
    (reference: layers/nn.py ctc_greedy_decoder)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    am = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", {"X": [input]}, {"Out": [am]},
                     {"axis": -1, "keepdims": False})
    out = helper.create_variable_for_type_inference("int64", True)
    ln = helper.create_variable_for_type_inference("int32", True)
    ins = {"Input": [am]}
    if input_length is not None:
        ins["InputLength"] = [input_length]
    helper.append_op("ctc_align", ins,
                     {"Output": [out], "OutputLength": [ln]},
                     {"blank": int(blank),
                      "padding_value": int(padding_value)})
    return out, ln


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """Levenshtein distance per row (reference: layers/nn.py
    edit_distance). Returns (distance [B,1] f32, seq_num [1])."""
    helper = LayerHelper("edit_distance", name=name)
    out = helper.create_variable_for_type_inference("float32", True)
    sn = helper.create_variable_for_type_inference("int64", True)
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    helper.append_op("edit_distance", ins,
                     {"Out": [out], "SequenceNum": [sn]},
                     {"normalized": bool(normalized)})
    return out, sn


def cos_sim(X, Y, name=None):
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", {"X": [X], "Y": [Y]},
                     {"Out": [out], "XNorm": [xn], "YNorm": [yn]}, {})
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", name=name)
    c = int(input.shape[1])
    scale = helper.create_parameter(param_attr or ParamAttr(), [c],
                                    input.dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr or ParamAttr(), [c], input.dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    sm = helper.create_variable_for_type_inference(input.dtype, True)
    sv = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("instance_norm",
                     {"X": [input], "Scale": [scale], "Bias": [bias]},
                     {"Y": [out], "SavedMean": [sm], "SavedVariance": [sv]},
                     {"epsilon": epsilon})
    return out


def auc(input, label, num_thresholds=4095, name=None):
    """Streaming AUC metric (reference: layers auc / metrics/auc_op.cc).
    Returns (auc_value, [stat_pos, stat_neg]) — state vars accumulate."""
    helper = LayerHelper("auc", name=name)
    pos = create_global_var([num_thresholds + 1], 0.0, "float32",
                            persistable=True,
                            name=unique_name.generate("auc_stat_pos"))
    neg = create_global_var([num_thresholds + 1], 0.0, "float32",
                            persistable=True,
                            name=unique_name.generate("auc_stat_neg"))
    out = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("auc",
                     {"Predict": [input], "Label": [label],
                      "StatPos": [pos], "StatNeg": [neg]},
                     {"AUC": [out], "StatPosOut": [pos], "StatNegOut": [neg]},
                     {"num_thresholds": num_thresholds})
    return out, [pos, neg]


def take_along_axis(input, index, axis, name=None):
    """Batched gather along `axis` with broadcastable index
    (ops/extra_ops.py take_along_axis; numpy semantics)."""
    helper = LayerHelper("take_along_axis", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("take_along_axis", {"Input": [input], "Index": [index]},
                     {"Result": [out]}, {"Axis": axis})
    return out


def switch_moe(x, num_experts, d_ff, capacity_factor=1.25, axis_name="ep",
               ep_size=1, activation="gelu", param_attr=None, name=None,
               tokens_sharded=False):
    """Switch-Transformer MoE FFN (ops/moe_ops.py, parallel/moe.py): top-1
    routing with capacity; expert weights sharded over the 'ep' mesh axis.
    Returns (out, aux_loss) — add aux_loss (scaled ~1e-2) to the training
    loss. `ep_size` sets the collective rank requirement (the mesh's ep
    extent; 1 = single device holds all experts).

    tokens_sharded=True: the token batch is data-parallel over the SAME
    'ep' axis (dp x ep composition) — token slots travel to their
    expert's rank and back via all_to_all (GShard dispatch) instead of
    being replicated."""
    from ..parallel.api import shard_tensor

    helper = LayerHelper("switch_moe", name=name)
    h = int(x.shape[-1])
    dtype = x.dtype

    def _attr(suffix):
        base = ParamAttr._to_attr(param_attr) or ParamAttr()
        import copy

        a = copy.copy(base)
        a.name = unique_name.generate((name or "moe") + suffix)
        return a

    gate_w = helper.create_parameter(_attr("_gate"), [h, num_experts], dtype)
    w1 = helper.create_parameter(_attr("_w1"), [num_experts, h, d_ff], dtype)
    b1 = helper.create_parameter(_attr("_b1"), [num_experts, d_ff], dtype,
                                 is_bias=True)
    w2 = helper.create_parameter(_attr("_w2"), [num_experts, d_ff, h], dtype)
    b2 = helper.create_parameter(_attr("_b2"), [num_experts, h], dtype,
                                 is_bias=True)
    for p in (w1, b1, w2, b2):
        shard_tensor(p, ("ep",) + (None,) * (len(p.shape) - 1))
    out = helper.create_variable_for_type_inference(dtype)
    out.desc.shape = list(x.shape)     # op is skip_infer_shape
    # aux MUST be differentiable — it is the router's only balancing signal
    aux = helper.create_variable_for_type_inference("float32")
    aux.desc.shape = []
    helper.append_op("switch_moe",
                     {"X": [x], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                      "W2": [w2], "B2": [b2]},
                     {"Out": [out], "AuxLoss": [aux]},
                     {"capacity_factor": capacity_factor,
                      "axis_name": axis_name, "activation": activation,
                      "tokens_sharded": bool(tokens_sharded),
                      "nranks": int(ep_size)})
    return out, aux


def masked_select(x, mask, name=None):
    """reference: masked_select_op.cc via python masked_select API. Static
    form returns (values, count): values is padded to x.size with the
    first `count` slots holding the selected elements."""
    helper = LayerHelper("masked_select", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    cnt = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("masked_select", {"X": [x], "Mask": [mask]},
                     {"Y": [out], "Count": [cnt]}, {})
    return out, cnt


def partial_sum(input, start_index=0, length=-1, name=None):
    """reference: contrib partial_sum (partial_sum_op.cc)."""
    helper = LayerHelper("partial_sum", name=name)
    xs = input if isinstance(input, (list, tuple)) else [input]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("partial_sum", {"X": list(xs)}, {"Out": [out]},
                     {"start_index": int(start_index), "length": int(length)})
    return out


def partial_concat(input, start_index=0, length=-1, name=None):
    """reference: contrib partial_concat (partial_concat_op.cc)."""
    helper = LayerHelper("partial_concat", name=name)
    xs = input if isinstance(input, (list, tuple)) else [input]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("partial_concat", {"X": list(xs)}, {"Out": [out]},
                     {"start_index": int(start_index), "length": int(length)})
    return out


def py_func(func, x, out, backward_func=None, name=None):
    """reference: python/paddle/fluid/layers/nn.py py_func (py_func_op.cc)
    — run a Python callable as a program op via jax.pure_callback.

    `out` vars must be pre-created with concrete shape/dtype (the host
    round-trip needs static result shapes). backward_func, if given,
    receives (*forward_inputs, *out_grads) and returns one grad per
    forward input."""
    from ..ops.extra_ops4 import register_py_func

    helper = LayerHelper("py_func", name=name)
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        if o.shape is None or any(int(d) < 0 for d in o.shape):
            raise ValueError(
                "py_func out vars need fully static shapes (got "
                f"{o.name}: {o.shape})")
    attrs = {
        "callable_id": register_py_func(func),
        "out_shapes": [[int(d) for d in o.shape] for o in outs],
        "out_dtypes": [str(o.dtype) for o in outs],
        "backward_callable_id": (
            register_py_func(backward_func) if backward_func else -1),
        "in_shapes_for_grad": [[int(d) for d in v.shape] for v in xs],
        "in_dtypes_for_grad": [str(v.dtype) for v in xs],
    }
    helper.append_op("py_func", {"X": list(xs)}, {"Out": list(outs)}, attrs)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: fluid.layers.spectral_norm (spectral_norm_op.cc).
    Creates the persistent U/V power-iteration vectors and threads the
    op's UOut/VOut back through them (the reference mutates U/V in
    place), so one iteration per step converges over training."""
    from ..initializer import Normal

    helper = LayerHelper("spectral_norm", name=name)
    h = int(weight.shape[dim])
    w = int(np.prod([int(d) for i, d in enumerate(weight.shape)
                     if i != dim]))
    u = helper.create_parameter(
        ParamAttr(name=unique_name.generate((name or "spectral_norm")
                                            + ".u"),
                  initializer=Normal(0.0, 1.0), trainable=False),
        [h], "float32")
    v = helper.create_parameter(
        ParamAttr(name=unique_name.generate((name or "spectral_norm")
                                            + ".v"),
                  initializer=Normal(0.0, 1.0), trainable=False),
        [w], "float32")
    u.stop_gradient, v.stop_gradient = True, True
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op("spectral_norm",
                     {"Weight": [weight], "U": [u], "V": [v]},
                     {"Out": [out], "UOut": [u], "VOut": [v]},
                     {"dim": int(dim), "power_iters": int(power_iters),
                      "eps": float(eps)})
    return out
