"""fluid-style LR schedule layers (reference:
python/paddle/fluid/layers/learning_rate_scheduler.py — noam_decay:57,
exponential_decay:114, natural_exp_decay:167, inverse_time_decay:218,
polynomial_decay:269, piecewise_decay:332, cosine_decay:387,
linear_lr_warmup:436).

Each returns a [1] float32 Variable produced by the `lr_schedule` op, which
reads the executor's global step — pass it as `learning_rate=` to any
optimizer. The reference builds these from counter/scale/cond op chains;
here the whole schedule is one op that XLA folds into the step program.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core import unique_name
from ..core.ir import Variable, default_main_program

__all__ = ["noam_decay", "exponential_decay", "natural_exp_decay",
           "inverse_time_decay", "polynomial_decay", "piecewise_decay",
           "cosine_decay", "linear_lr_warmup"]


_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter() -> Variable:
    """Shared auto-incremented step var (reference:
    layers/learning_rate_scheduler.py _decay_step_counter — an `increment`
    op inside the main program, so the count tracks MAIN-program runs, not
    arbitrary executor runs). Initialised to -1; first run reads 0."""
    from ..core.ir import OpRole
    from .nn import create_global_var

    prog = default_main_program()
    block = prog.global_block()
    if _COUNTER_NAME in block.vars:
        return block.vars[_COUNTER_NAME]
    counter = create_global_var([1], -1.0, "float32", persistable=True,
                                name=_COUNTER_NAME)
    # LRSched role (reference: program.lr_schedule_guard) so the PS
    # transpiler moves the counter increment to the pserver, where it
    # advances once per GLOBAL step
    with prog._role_guard(OpRole.LRSched):
        block.append_op("increment", {"X": [counter]}, {"Out": [counter]},
                        {"step": 1.0}, infer_shape=False)
    return counter


def _lr_op(schedule: str, attrs: dict, base_lr: Optional[Variable] = None,
           name: str = "learning_rate") -> Variable:
    from ..core.ir import OpRole

    prog = default_main_program()
    block = prog.current_block()
    step = _decay_step_counter()
    out = block.create_var(name=unique_name.generate(name), shape=(1,),
                           dtype="float32", persistable=True)
    ins = {"Step": [step]}
    if base_lr is not None:
        ins["BaseLR"] = [base_lr]
    with prog._role_guard(OpRole.LRSched):
        block.append_op("lr_schedule", ins, {"Out": [out]},
                        {"schedule": schedule, **attrs}, infer_shape=False)
    return out


def noam_decay(d_model: int, warmup_steps: int, learning_rate: float = 1.0):
    """lr · d_model^-0.5 · min(step^-0.5, step·warmup^-1.5)."""
    return _lr_op("noam", {"d_model": d_model, "warmup_steps": warmup_steps,
                           "learning_rate": learning_rate})


def exponential_decay(learning_rate: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False):
    return _lr_op("exponential", {"learning_rate": learning_rate,
                                  "decay_steps": decay_steps,
                                  "decay_rate": decay_rate,
                                  "staircase": staircase})


def natural_exp_decay(learning_rate: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False):
    return _lr_op("natural_exp", {"learning_rate": learning_rate,
                                  "decay_steps": decay_steps,
                                  "decay_rate": decay_rate,
                                  "staircase": staircase})


def inverse_time_decay(learning_rate: float, decay_steps: int,
                       decay_rate: float, staircase: bool = False):
    return _lr_op("inverse_time", {"learning_rate": learning_rate,
                                   "decay_steps": decay_steps,
                                   "decay_rate": decay_rate,
                                   "staircase": staircase})


def polynomial_decay(learning_rate: float, decay_steps: int,
                     end_learning_rate: float = 1e-4, power: float = 1.0,
                     cycle: bool = False):
    return _lr_op("polynomial", {"learning_rate": learning_rate,
                                 "decay_steps": decay_steps,
                                 "end_learning_rate": end_learning_rate,
                                 "power": power, "cycle": cycle})


def piecewise_decay(boundaries: Sequence[int], values: Sequence[float]):
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    return _lr_op("piecewise", {"boundaries": [float(b) for b in boundaries],
                                "values": [float(v) for v in values]})


def cosine_decay(learning_rate: float, step_each_epoch: int, epochs: int):
    return _lr_op("cosine", {"learning_rate": learning_rate,
                             "step_each_epoch": step_each_epoch,
                             "epochs": epochs})


def linear_lr_warmup(learning_rate, warmup_steps: int, start_lr: float,
                     end_lr: float):
    """Linear ramp start_lr→end_lr over warmup_steps, then the base schedule
    (a float or another schedule's Variable)."""
    attrs = {"warmup_steps": warmup_steps, "start_lr": start_lr,
             "end_lr": end_lr}
    if isinstance(learning_rate, Variable):
        return _lr_op("linear_warmup", attrs, base_lr=learning_rate)
    return _lr_op("linear_warmup", {**attrs, "base_lr": float(learning_rate)})
