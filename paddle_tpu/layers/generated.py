"""Table-generated fluid.layers functions over registered ops.

Capability mirror of the reference's layer_function_generator
(python/paddle/fluid/layers/layer_function_generator.py): most of
fluid.layers' 156-function surface is mechanical op wrapping, which the
reference generates from OpProto. Here the table maps each layer name to
its op's input slots / primary output (same slot names as the
reference's op protos); multi-output ops create all outputs and return
the primary, exactly like the generated reference layers.

Compositions (has_inf, smooth_l1, dice_loss, mean_iou, case, ...) that
the reference writes by hand over other layers are written by hand over
other layers here too.
"""

from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper

__all__ = []


def _register(name, fn):
    fn.__name__ = name
    globals()[name] = fn
    __all__.append(name)
    return fn


def generate_layer_fn(op_type, in_slots, out_slots, primary=None, doc=""):
    """A fluid-layers-style function for `op_type`: positional args map
    to `in_slots`, keyword args become op attrs, returns the primary
    output var (reference: layer_function_generator.generate_layer_fn)."""
    primary = primary or out_slots[0]

    def fn(*args, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        if len(args) > len(in_slots):
            raise TypeError(f"{op_type}() takes at most {len(in_slots)} "
                            f"positional args ({in_slots})")
        dtype = None
        inputs = {}
        for slot, arg in zip(in_slots, args):
            if arg is None:
                continue
            inputs[slot] = list(arg) if isinstance(arg, (list, tuple)) \
                else [arg]
            if dtype is None:
                v = inputs[slot][0]
                dtype = getattr(v, "dtype", None)
        outs = {s: [helper.create_variable_for_type_inference(
            attrs.get("dtype", dtype or "float32"))] for s in out_slots}
        helper.append_op(op_type, inputs, outs, attrs)
        return outs[primary][0]

    fn.__doc__ = doc or (f"Generated wrapper over the `{op_type}` op "
                         f"(inputs {in_slots} -> {primary}).")
    return fn


# --- (name, op_type, input slots, output slots[, primary]) ---------------
_TABLE = [
    # unary activations / elementwise
    ("brelu", "brelu", ["X"], ["Out"]),
    ("hard_shrink", "hard_shrink", ["X"], ["Out"]),
    ("hard_sigmoid", "hard_sigmoid", ["X"], ["Out"]),
    ("hard_swish", "hard_swish", ["X"], ["Out"]),
    ("mish", "mish", ["X"], ["Out"]),
    ("stanh", "stanh", ["X"], ["Out"]),
    ("logical_not", "logical_not", ["X"], ["Out"]),
    ("isfinite", "isfinite", ["X"], ["Out"]),
    ("reverse", "reverse", ["X"], ["Out"]),
    ("clip_by_norm", "clip_by_norm", ["X"], ["Out"]),
    ("is_empty", "is_empty", ["X"], ["Out"]),
    ("reduce_all", "reduce_all", ["X"], ["Out"]),
    ("reduce_any", "reduce_any", ["X"], ["Out"]),
    # binary / comparison / logical
    ("logical_and", "logical_and", ["X", "Y"], ["Out"]),
    ("logical_or", "logical_or", ["X", "Y"], ["Out"]),
    ("logical_xor", "logical_xor", ["X", "Y"], ["Out"]),
    ("less_equal", "less_equal", ["X", "Y"], ["Out"]),
    ("greater_equal", "greater_equal", ["X", "Y"], ["Out"]),
    ("elementwise_floordiv", "elementwise_floordiv", ["X", "Y"], ["Out"]),
    # gather/scatter family
    ("gather_nd", "gather_nd", ["X", "Index"], ["Out"]),
    ("scatter", "scatter", ["X", "Ids", "Updates"], ["Out"]),
    ("scatter_nd", "scatter_nd", ["Index", "Updates"], ["Out"]),
    ("scatter_nd_add", "scatter_nd_add", ["X", "Index", "Updates"],
     ["Out"]),
    ("multiplex", "multiplex", ["X", "Ids"], ["Out"]),
    ("gather_tree", "gather_tree", ["Ids", "Parents"], ["Out"]),
    # shapes / tensor utilities
    ("shape", "shape", ["Input"], ["Out"]),
    ("size", "size", ["Input"], ["Out"]),
    ("diag", "diag", ["Diagonal"], ["Out"]),
    ("strided_slice", "strided_slice", ["Input"], ["Out"]),
    ("crop", "crop", ["X", "Y"], ["Out"]),
    ("crop_tensor", "crop_tensor", ["X", "Shape", "Offsets"], ["Out"]),
    ("pad_constant_like", "pad_constant_like", ["X", "Y"], ["Out"]),
    ("expand_as", "expand_as", ["X", "target_tensor"], ["Out"]),
    ("space_to_depth", "space_to_depth", ["X"], ["Out"]),
    ("shard_index", "shard_index", ["X"], ["Out"]),
    ("shuffle_channel", "shuffle_channel", ["X"], ["Out"]),
    ("temporal_shift", "temporal_shift", ["X"], ["Out"]),
    ("hash", "hash", ["X"], ["Out"]),
    ("im2sequence", "im2sequence", ["X"], ["Out"]),
    ("sampling_id", "sampling_id", ["X"], ["Out"]),
    ("add_position_encoding", "add_position_encoding", ["X"], ["Out"]),
    ("get_tensor_from_selected_rows", "get_tensor_from_selected_rows",
     ["X"], ["Out"]),
    ("merge_selected_rows", "merge_selected_rows", ["X"], ["Out"]),
    ("lod_reset", "lod_reset", ["X", "Y"], ["Out"]),
    # random creators
    ("uniform_random", "uniform_random", [], ["Out"]),
    ("gaussian_random", "gaussian_random", [], ["Out"]),
    ("fill_constant_batch_size_like", "fill_constant_batch_size_like",
     ["Input"], ["Out"]),
    ("gaussian_random_batch_size_like",
     "gaussian_random_batch_size_like", ["Input"], ["Out"]),
    ("uniform_random_batch_size_like",
     "uniform_random_batch_size_like", ["Input"], ["Out"]),
    # norm / vision / conv
    ("pad2d", "pad2d", ["X"], ["Out"]),
    ("lrn", "lrn", ["X"], ["Out", "MidOut"], "Out"),
    ("data_norm", "data_norm",
     ["X", "BatchSize", "BatchSum", "BatchSquareSum"],
     ["Y", "Means", "Scales"], "Y"),
    ("grid_sampler", "grid_sampler", ["X", "Grid"], ["Output"]),
    ("roi_align", "roi_align", ["X", "ROIs"], ["Out"]),
    ("roi_pool", "roi_pool", ["X", "ROIs", "RoisNum"],
     ["Out", "Argmax"], "Out"),
    ("affine_channel", "affine_channel", ["X", "Scale", "Bias"], ["Out"]),
    ("affine_grid", "affine_grid", ["Theta", "OutputShape"], ["Output"]),
    ("row_conv", "row_conv", ["X", "Filter"], ["Out"]),
    ("conv3d", "conv3d", ["Input", "Filter"], ["Output"]),
    ("conv3d_transpose", "conv3d_transpose", ["Input", "Filter"],
     ["Output"]),
    ("pool3d", "pool3d", ["X"], ["Out"]),
    ("maxout", "maxout", ["X"], ["Out"]),
    # losses
    ("rank_loss", "rank_loss", ["Label", "Left", "Right"], ["Out"]),
    ("margin_rank_loss", "margin_rank_loss", ["Label", "X1", "X2"],
     ["Out", "Activated"], "Out"),
    ("huber_loss", "huber_loss", ["X", "Y"], ["Out", "Residual"], "Out"),
    ("kldiv_loss", "kldiv_loss", ["X", "Target"], ["Loss"]),
    ("log_loss", "log_loss", ["Predicted", "Labels"], ["Loss"]),
    ("bpr_loss", "bpr_loss", ["X", "Label"], ["Y"]),
    ("sigmoid_focal_loss", "sigmoid_focal_loss", ["X", "Label", "FgNum"],
     ["Out"]),
    ("teacher_student_sigmoid_loss", "teacher_student_sigmoid_loss",
     ["X", "Label"], ["Y"]),
    ("center_loss", "center_loss",
     ["X", "Label", "Centers", "CenterUpdateRate"],
     ["Loss", "SampleCenterDiff", "CentersOut"], "Loss"),
    # RNN / misc op zoo
    ("lstm", "lstm",
     ["Input", "WeightX", "WeightH", "Bias", "H0", "C0", "SequenceLength"],
     ["Out", "LastH", "LastC"], "Out"),
    ("gru_unit", "gru_unit", ["Input", "HiddenPrev", "Weight", "Bias"],
     ["Hidden", "ResetHiddenPrev", "Gate"], "Hidden"),
    ("lstm_unit", "lstm_unit", ["X", "C_prev"], ["H", "C"], "H"),
    ("nce", "nce", ["Input", "Label", "Weight", "Bias"],
     ["Cost", "SampleLogits", "SampleLabels"], "Cost"),
    ("warpctc", "warpctc",
     ["Logits", "Label", "LogitsLength", "LabelLength"],
     ["Loss", "WarpCTCGrad"], "Loss"),
    ("bilinear_tensor_product", "bilinear_tensor_product",
     ["X", "Y", "Weight", "Bias"], ["Out"]),
    ("filter_by_instag", "filter_by_instag",
     ["Ins", "Ins_tag", "Filter_tag"],
     ["Out", "LossWeight", "IndexMap", "Count"], "Out"),
    ("chunk_eval", "chunk_eval", ["Inference", "Label", "SeqLength"],
     ["Precision", "Recall", "F1-Score", "NumInferChunks",
      "NumLabelChunks", "NumCorrectChunks"], "Precision"),
    ("beam_search", "beam_search", ["pre_ids", "pre_scores", "scores"],
     ["selected_ids", "selected_scores", "parent_idx"], "selected_ids"),
    ("beam_search_decode", "beam_search_decode",
     ["Ids", "Scores", "ParentIdx"],
     ["SentenceIds", "SentenceScores"], "SentenceIds"),
    ("tensor_array_to_tensor", "tensor_array_to_tensor", ["X"],
     ["Out", "OutIndex"], "Out"),
    ("array_read", "array_read", ["X", "I"], ["Out"]),
    # sequence family (padded-dense + Lod/Length companions, the
    # repo-wide LoD re-design — sequence_ops.py)
    ("sequence_concat", "sequence_concat", ["X", "Lod"],
     ["Out", "OutLod"], "Out"),
    ("sequence_conv", "sequence_conv", ["X", "Filter"], ["Out"]),
    ("sequence_enumerate", "sequence_enumerate", ["X"], ["Out"]),
    ("sequence_expand", "sequence_expand", ["X", "RefLod"], ["Out"]),
    ("sequence_expand_as", "sequence_expand_as", ["X", "Y", "YLength"],
     ["Out", "OutLength"], "Out"),
    ("sequence_pad", "sequence_pad", ["X", "Lod", "PadValue"],
     ["Out", "Length"], "Out"),
    ("sequence_reshape", "sequence_reshape", ["X"], ["Out"]),
    ("sequence_reverse", "sequence_reverse", ["X", "Lod"], ["Y"]),
    ("sequence_scatter", "sequence_scatter", ["X", "Ids", "Updates"],
     ["Out"]),
    ("sequence_slice", "sequence_slice", ["X", "Offset", "Length"],
     ["Out", "OutLength"], "Out"),
    ("sequence_softmax", "sequence_softmax", ["X", "Lod"], ["Out"]),
    ("sequence_unpad", "sequence_unpad", ["X", "Length"], ["Out"]),
    ("unfold", "unfold", ["X"], ["Y"]),
    ("unbind", "unbind", ["X"], ["Out"]),
    ("load", "load", [], ["Out"]),
    ("lod_append", "lod_reset", ["X", "Y"], ["Out"]),
    ("inplace_abn", "inplace_abn",
     ["X", "Scale", "Bias", "Mean", "Variance"],
     ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"], "Y"),
]

for _row in _TABLE:
    _name, _op = _row[0], _row[1]
    _register(_name, generate_layer_fn(_op, _row[2], _row[3],
                                       _row[4] if len(_row) > 4 else None))


def _aw(x, i, array, name=None):
    helper = LayerHelper("array_write", name=name)
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("array_write", {"X": [array], "I": [i], "V": [x]},
                     {"Out": [out]}, {})
    return out


_register("array_write", _aw)


# --- cross-namespace aliases (same callable, fluid.layers name) ----------

def _install_aliases():
    from .. import tensor as _tensor
    from ..nn import functional as _F

    for name in ("argsort", "cumsum", "eye", "linspace", "pow", "argmin",
                 "triu", "unique", "unbind", "unstack", "gather_tree"):
        if name not in globals() and hasattr(_tensor, name):
            _register(name, getattr(_tensor, name))
    for name in ("elu", "relu6", "selu", "softshrink", "thresholded_relu",
                 "pixel_shuffle", "mse_loss", "group_norm", "pad"):
        if name not in globals() and hasattr(_F, name):
            _register(name, getattr(_F, name))


_install_aliases()


# --- hand compositions (the reference writes these over layers too) ------

def _compose():
    from .. import layers as L

    def sums(input, out=None, name=None):
        helper = LayerHelper("sum", name=name)
        res = out or helper.create_variable_for_type_inference(
            input[0].dtype)
        helper.append_op("sum", {"X": list(input)}, {"Out": [res]}, {})
        return res

    _register("sums", sums)
    _register("sum", sums)

    def has_nan(x, name=None):
        return globals()["reduce_any"](L.not_equal(x, x))

    def has_inf(x, name=None):
        # inf = non-finite that is not nan
        bad = L.logical_not(globals()["isfinite"](x))
        notnan = L.equal(x, x)
        return globals()["reduce_any"](L.logical_and(bad, notnan))

    _register("has_nan", has_nan)
    _register("has_inf", has_inf)

    def rank(input, name=None):
        return L.fill_constant([1], "int32", len(input.shape or ()))

    _register("rank", rank)

    def range_(start, end, step, dtype="int64", name=None):
        def as_var(v):
            return v if hasattr(v, "block") else \
                L.fill_constant([1], dtype, float(v))

        helper = LayerHelper("range", name=name)
        out = helper.create_variable_for_type_inference(dtype)
        helper.append_op("range", {"Start": [as_var(start)],
                                   "End": [as_var(end)],
                                   "Step": [as_var(step)]},
                         {"Out": [out]}, {"dtype": dtype})
        return out

    _register("range", range_)

    def sequence_first_step(input, length=None, name=None):
        return L.sequence_pool(input, "first", length=length)

    def sequence_last_step(input, length=None, name=None):
        return L.sequence_pool(input, "last", length=length)

    _register("sequence_first_step", sequence_first_step)
    _register("sequence_last_step", sequence_last_step)

    def dice_loss(input, label, epsilon=1e-5, name=None):
        """reference: fluid/layers/nn.py dice_loss — composed over
        one-hot/reduce ops exactly like the reference's python body."""
        label = L.squeeze(label, [-1])
        label = L.one_hot(label, depth=input.shape[-1])
        reduce_dims = list(range(1, len(input.shape)))
        inse = L.reduce_sum(input * label, dim=reduce_dims)
        dice_denominator = L.reduce_sum(input, dim=reduce_dims) + \
            L.reduce_sum(label, dim=reduce_dims)
        dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
        return L.reduce_mean(dice_score)

    _register("dice_loss", dice_loss)

    def smooth_l1(x, y, inside_weight=None, outside_weight=None,
                  sigma=1.0, name=None):
        """reference: operators/smooth_l1_loss_op.cc semantics composed
        from elementwise ops (per-row summed smooth-L1)."""
        sigma2 = float(sigma) * float(sigma)
        d = x - y
        if inside_weight is not None:
            d = d * inside_weight
        ad = L.abs(d)
        flag = L.cast(L.less_than(ad, L.fill_constant(
            [1], x.dtype, 1.0 / sigma2)), x.dtype)
        val = flag * 0.5 * sigma2 * d * d + \
            (1.0 - flag) * (ad - 0.5 / sigma2)
        if outside_weight is not None:
            val = val * outside_weight
        return L.reduce_sum(val, dim=[1], keep_dim=True)

    _register("smooth_l1", smooth_l1)

    def mean_iou(input, label, num_classes, name=None):
        """reference: operators/mean_iou_op.cc — per-class IoU from
        one-hot intersection/union counts; returns (mean_iou,
        out_wrong, out_correct)."""
        pred = L.reshape(input, [-1])
        lab = L.reshape(label, [-1])
        po = L.one_hot(pred, depth=num_classes)
        lo = L.one_hot(lab, depth=num_classes)
        inter = L.reduce_sum(po * lo, dim=[0])
        union = L.reduce_sum(po, dim=[0]) + L.reduce_sum(lo, dim=[0]) \
            - inter
        valid = L.cast(L.greater_than(
            union, L.fill_constant([1], union.dtype, 0.0)), union.dtype)
        iou = inter / (union + 1e-9)
        miou = L.reduce_sum(iou) / (L.reduce_sum(valid) + 1e-9)
        wrong = L.cast(L.reduce_sum(po, dim=[0]) - inter, "int32")
        correct = L.cast(inter, "int32")
        return miou, wrong, correct

    _register("mean_iou", mean_iou)

    def case(pred_fn_pairs, default=None, name=None):
        """reference: fluid/layers/control_flow.py case() — nested
        cond over the ordered (pred, fn) pairs."""
        from .control_flow import cond as _cond

        def build(pairs):
            (pred, fn) = pairs[0]
            if len(pairs) == 1:
                if default is None:
                    return _cond(pred, fn, fn)
                return _cond(pred, fn, default)
            return _cond(pred, fn, lambda: build(pairs[1:]))

        return build(list(pred_fn_pairs))

    _register("case", case)

    def switch_case(branch_index, branch_fns, default=None, name=None):
        """reference: control_flow.py switch_case() — dispatch on an
        int32 scalar via chained equals."""
        items = sorted(branch_fns.items()) if isinstance(branch_fns, dict) \
            else list(branch_fns)
        pairs = [(L.equal(branch_index,
                          L.fill_constant([1], "int64", float(i))), fn)
                 for i, fn in items]
        return case(pairs, default=default)

    _register("switch_case", switch_case)

    def create_array(dtype, initialized_list=None):
        """Modernised LoDTensorArray creator: a stacked buffer var
        (control-flow ops array_read/array_write operate on it)."""
        return L.fill_constant([0], dtype, 0.0)

    _register("create_array", create_array)

    def array_length(array, name=None):
        return L.slice(globals()["shape"](array), [0], [0], [1])

    _register("array_length", array_length)

    def resize_nearest(input, out_shape=None, scale=None, name=None,
                       **kw):
        attrs = {"interp_method": "nearest"}
        if out_shape is not None:
            attrs["out_h"], attrs["out_w"] = int(out_shape[0]), \
                int(out_shape[1])
        if scale is not None:
            attrs["scale"] = float(scale)
        helper = LayerHelper("nearest_interp", name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("nearest_interp", {"X": [input]}, {"Out": [out]},
                         attrs)
        return out

    def resize_bilinear(input, out_shape=None, scale=None, name=None,
                        **kw):
        attrs = {"interp_method": "bilinear"}
        if out_shape is not None:
            attrs["out_h"], attrs["out_w"] = int(out_shape[0]), \
                int(out_shape[1])
        if scale is not None:
            attrs["scale"] = float(scale)
        helper = LayerHelper("bilinear_interp", name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("bilinear_interp", {"X": [input]}, {"Out": [out]},
                         attrs)
        return out

    def image_resize(input, out_shape=None, scale=None, name=None,
                     resample="BILINEAR", **kw):
        if resample.upper().startswith("NEAREST"):
            return resize_nearest(input, out_shape, scale, name)
        return resize_bilinear(input, out_shape, scale, name)

    _register("resize_bilinear", resize_bilinear)
    _register("resize_nearest", resize_nearest)
    _register("image_resize", image_resize)

    def prelu(x, mode="all", param_attr=None, name=None):
        """reference: fluid/layers/nn.py prelu — learnable alpha with
        'all'/'channel'/'element' granularity."""
        helper = LayerHelper("prelu", name=name)
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [int(x.shape[1])]
        else:
            shape = [int(d) for d in x.shape[1:]]
        alpha = helper.create_parameter(param_attr, shape, x.dtype)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op("prelu", {"X": [x], "Alpha": [alpha]},
                         {"Out": [out]}, {"mode": mode})
        return out

    if "prelu" not in globals():
        _register("prelu", prelu)

    def soft_relu(x, threshold=40.0, name=None):
        """reference: ops.py soft_relu — log(1 + exp(clip(x, -t, t)))."""
        return L.log(1.0 + L.exp(L.clip(x, -float(threshold),
                                        float(threshold))))

    _register("soft_relu", soft_relu)

    def create_tensor(dtype, name=None, persistable=False):
        from ..core.ir import default_main_program

        return default_main_program().global_block().create_var(
            name=name, dtype=dtype, persistable=persistable)

    _register("create_tensor", create_tensor)

    def autoincreased_step_counter(counter_name=None, begin=1, step=1,
                                   name=None):
        """reference: layers/tensor.py — a persistable int64 counter
        incremented every step."""
        var = L.create_global_var([1], float(begin - step), "int64",
                                  persistable=True,
                                  name=counter_name or "@@step_counter@@")
        helper = LayerHelper("increment")
        helper.append_op("increment", {"X": [var]}, {"Out": [var]},
                         {"step": float(step)})
        return var

    _register("autoincreased_step_counter", autoincreased_step_counter)

    def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
        """reference: layers/loss.py npair_loss — composed identically
        (similarity matrix CE + L2 regulariser)."""
        lab = L.reshape(labels, [-1, 1])
        same = L.cast(L.equal(lab, L.transpose(lab, [1, 0])), "float32")
        w = same / L.reduce_sum(same, dim=[1], keep_dim=True)
        sim = L.matmul(anchor, positive, transpose_y=True)
        logp = sim - L.log(L.reduce_sum(L.exp(sim), dim=[1],
                                        keep_dim=True))
        ce = L.reduce_mean(-L.reduce_sum(w * logp, dim=[1]))
        reg = L.reduce_mean(L.reduce_sum(anchor * anchor, dim=[1])
                            + L.reduce_sum(positive * positive, dim=[1]))             * (l2_reg * 0.25)
        return ce + reg

    _register("npair_loss", npair_loss)

    def fsp_matrix(x, y):
        """reference: layers/nn.py fsp_matrix — flow-of-solution-
        procedure Gram matrix between two feature maps."""
        b = x.shape[0]
        cx, cy = x.shape[1], y.shape[1]
        xf = L.reshape(x, [b, cx, -1])
        yf = L.reshape(y, [b, cy, -1])
        hw = int(np.prod(x.shape[2:]))
        return L.matmul(xf, L.transpose(yf, [0, 2, 1])) * (1.0 / hw)

    _register("fsp_matrix", fsp_matrix)

    def image_resize_short(input, out_short_len, resample="BILINEAR"):
        h, w = int(input.shape[2]), int(input.shape[3])
        short = min(h, w)
        oh = int(round(h * out_short_len / short))
        ow = int(round(w * out_short_len / short))
        return image_resize(input, out_shape=[oh, ow], resample=resample)

    _register("image_resize_short", image_resize_short)

    def _multi_out(op_type, in_map, out_slots, n_return):
        def fn(x, name=None, **attrs):
            helper = LayerHelper(op_type, name=name)
            outs = {s: [helper.create_variable_for_type_inference(
                x.dtype if i == 0 else "int64")]
                for i, s in enumerate(out_slots)}
            helper.append_op(op_type, {in_map: [x]}, outs, attrs)
            vals = [outs[s][0] for s in out_slots]
            return tuple(vals[:n_return]) if n_return > 1 else vals[0]

        return fn

    _register("unstack", _multi_out("unstack", "X", ["Y"], 1))
    _register("unique", _multi_out("unique", "X",
                                   ["Out", "Index", "Count"], 2))
    _register("unique_with_counts", _multi_out(
        "unique_with_counts", "X", ["Out", "Index", "Count"], 3))

    def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                     bias_attr=None, use_peepholes=False,
                     is_reverse=False, gate_activation="sigmoid",
                     cell_activation="tanh", candidate_activation="tanh",
                     dtype="float32", name=None, sequence_length=None):
        """reference: layers/nn.py dynamic_lstm — input is the
        PRE-PROJECTED [B,S,4H] gates; this creates WeightH/Bias and
        runs the lstm op with the projection folded (WeightX absent)."""
        h = size // 4
        helper = LayerHelper("dynamic_lstm", name=name)
        wh = helper.create_parameter(param_attr, [h, 4 * h], dtype)
        b = helper.create_parameter(bias_attr, [4 * h], dtype,
                                    is_bias=True)
        outs = {s: [helper.create_variable_for_type_inference(dtype)]
                for s in ("Out", "LastH", "LastC")}
        ins = {"Input": [input], "WeightH": [wh], "Bias": [b]}
        if h_0 is not None:
            ins["H0"] = [h_0]
        if c_0 is not None:
            ins["C0"] = [c_0]
        if sequence_length is not None:
            ins["SequenceLength"] = [sequence_length]
        helper.append_op("lstm", ins, outs, {"is_reverse": is_reverse})
        return outs["Out"][0], outs["LastC"][0]

    _register("dynamic_lstm", dynamic_lstm)

    def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                    is_reverse=False, h_0=None, dtype="float32",
                    name=None, sequence_length=None, **kw):
        """reference: layers/nn.py dynamic_gru — input pre-projected
        [B,S,3H]; creates WeightH/Bias, runs the gru op."""
        helper = LayerHelper("dynamic_gru", name=name)
        wh = helper.create_parameter(param_attr, [size, 3 * size], dtype)
        b = helper.create_parameter(bias_attr, [3 * size], dtype,
                                    is_bias=True)
        outs = {s: [helper.create_variable_for_type_inference(dtype)]
                for s in ("Out", "LastH")}
        ins = {"Input": [input], "WeightH": [wh], "Bias": [b]}
        if h_0 is not None:
            ins["H0"] = [h_0]
        if sequence_length is not None:
            ins["SequenceLength"] = [sequence_length]
        helper.append_op("gru", ins, outs, {"is_reverse": is_reverse})
        return outs["Out"][0]

    _register("dynamic_gru", dynamic_gru)

    def dynamic_lstmp(input, size, proj_size, param_attr=None,
                      bias_attr=None, dtype="float32", name=None, **kw):
        """reference: layers/nn.py dynamic_lstmp over the lstmp op."""
        h = size // 4
        helper = LayerHelper("dynamic_lstmp", name=name)
        w = helper.create_parameter(param_attr, [proj_size, 4 * h], dtype)
        pw = helper.create_parameter(None, [h, proj_size], dtype)
        b = helper.create_parameter(bias_attr, [4 * h], dtype,
                                    is_bias=True)
        outs = {s: [helper.create_variable_for_type_inference(dtype)]
                for s in ("Projection", "Cell")}
        helper.append_op("lstmp", {"Input": [input], "Weight": [w],
                                   "ProjWeight": [pw], "Bias": [b]},
                         outs, {})
        return outs["Projection"][0], outs["Cell"][0]

    _register("dynamic_lstmp", dynamic_lstmp)


_compose()
