"""fluid-style layers API (reference: python/paddle/fluid/layers/)."""

from .nn import *  # noqa: F401,F403
from .nn import (_elementwise_binary, _compare, _getitem, _to_var,  # noqa: F401
                 _unary, _binary, _reduce_layer)
