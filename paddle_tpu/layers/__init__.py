"""fluid-style layers API (reference: python/paddle/fluid/layers/)."""

from .nn import *  # noqa: F401,F403
from .nn import (_elementwise_binary, _compare, _getitem, _to_var,  # noqa: F401
                 _unary, _binary, _reduce_layer)
from .learning_rate_scheduler import (  # noqa: F401
    cosine_decay, exponential_decay, inverse_time_decay, linear_lr_warmup,
    natural_exp_decay, noam_decay, piecewise_decay, polynomial_decay)
from .control_flow import (DynamicRNN, IfElse, array_to_lod_tensor,  # noqa: F401
                           cond, lod_rank_table, lod_tensor_to_array,
                           shrink_memory, static_loop, while_loop)

from . import generated as _generated  # noqa: E402
from .generated import *  # noqa: F401,F403,E402


_NN_CLASS_ALIASES = ("GRUCell", "LSTMCell")


def __getattr__(name):
    # fluid.layers re-exports the RNN cell classes (reference
    # fluid/layers/rnn.py); lazy since nn imports layers
    if name in _NN_CLASS_ALIASES:
        from .. import nn as _nn

        return getattr(_nn, name)
    raise AttributeError(f"module 'paddle_tpu.layers' has no attribute "
                         f"{name!r}")
