"""Profiler — host spans, summary tables, chrome-trace export, jax bridge.

Capability mirror of the reference profiler stack:
* ``RecordEvent`` RAII spans (platform/profiler.h:127; pushed per op run,
  framework/operator.cc:195) — here a context manager feeding a global
  event store;
* ``start_profiler``/``stop_profiler``/``reset_profiler`` + the
  ``profiler()`` context and sorted summary table
  (python/paddle/fluid/profiler.py, platform/profiler.cc PrintProfiler);
* chrome://tracing JSON export (tools/timeline.py) via
  ``export_chrome_tracing``;
* device-side tracing (platform/device_tracer.cc CUPTI) maps to the jax
  profiler (XPlane/TensorBoard): ``start_trace``/``stop_trace``.

The executor pushes spans automatically: per-op in the interpreting path,
per-step (compile + run) in the compiled path.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from typing import Dict, List, Optional

from .core import flags as _flags
from .core import telemetry as _telemetry
from .core.analysis import lockdep as _lockdep

_lock = _lockdep.lock("profiler.events")
_enabled = False
# {name, ts, dur, tid} — bounded ring: FLAGS_profiler_max_events caps the
# store so long training runs can't grow host memory without limit; when
# full, the OLDEST span is dropped (and counted in telemetry as
# profiler.events_dropped)
_events: "collections.deque[dict]" = collections.deque()


def _now_us() -> float:
    return time.perf_counter() * 1e6


class RecordEvent:
    """reference: platform/profiler.h:127 — RAII span; usable as a context
    manager or via push/pop."""

    def __init__(self, name: str):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()

    def begin(self):
        if _enabled:
            self._t0 = _now_us()

    def end(self):
        if self._t0 is None:
            return
        dur = _now_us() - self._t0
        dropped = 0
        cap = int(_flags.flag("profiler_max_events"))
        with _lock:
            while cap > 0 and len(_events) >= cap:
                _events.popleft()
                dropped += 1
            _events.append({"name": self.name, "ts": self._t0, "dur": dur,
                            "tid": threading.get_ident()})
        self._t0 = None
        if dropped:
            # outside _lock: counter_add takes the telemetry lock, and
            # telemetry.flush() takes locks in the opposite order
            _telemetry.counter_add("profiler.events_dropped", dropped)


@contextlib.contextmanager
def record_event(name: str):
    with RecordEvent(name):
        yield


def is_profiler_enabled() -> bool:
    return _enabled


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """reference: profiler.py start_profiler / EnableProfiler
    (profiler.h:209). `state`/`tracer_option` kept for API parity."""
    global _enabled
    reset_profiler()
    _enabled = True


def reset_profiler():
    with _lock:
        _events.clear()


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None):
    """Disable profiling, print the summary table, optionally dump the
    chrome trace (reference: DisableProfiler + PrintProfiler)."""
    global _enabled
    _enabled = False
    summary = summarize()
    _print_summary(summary, sorted_key)
    if profile_path:
        export_chrome_tracing(profile_path)
    return summary


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """with profiler.profiler(): ... (reference: fluid/profiler.py)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


profile = profiler  # alias


def events() -> List[dict]:
    with _lock:
        return list(_events)


def summarize() -> Dict[str, dict]:
    """Aggregate events by name → {calls, total_us, avg_us, max_us, min_us}."""
    agg: Dict[str, dict] = {}
    for e in events():
        s = agg.setdefault(e["name"], {"calls": 0, "total_us": 0.0,
                                       "max_us": 0.0, "min_us": float("inf")})
        s["calls"] += 1
        s["total_us"] += e["dur"]
        s["max_us"] = max(s["max_us"], e["dur"])
        s["min_us"] = min(s["min_us"], e["dur"])
    for s in agg.values():
        s["avg_us"] = s["total_us"] / s["calls"]
    return agg


def _print_summary(summary: Dict[str, dict], sorted_key: Optional[str]):
    if not summary:
        return
    key = {"total": "total_us", "calls": "calls", "max": "max_us",
           "min": "min_us", "ave": "avg_us", "avg": "avg_us"}.get(
               sorted_key or "total", "total_us")
    rows = sorted(summary.items(), key=lambda kv: kv[1][key], reverse=True)
    print(f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}{'Avg(us)':>12}"
          f"{'Max(us)':>12}{'Min(us)':>12}")
    for name, s in rows:
        print(f"{name[:39]:<40}{s['calls']:>8}{s['total_us']:>14.1f}"
              f"{s['avg_us']:>12.1f}{s['max_us']:>12.1f}{s['min_us']:>12.1f}")


def export_chrome_tracing(path: str):
    """chrome://tracing JSON (reference: tools/timeline.py output format)."""
    trace = {"traceEvents": [
        {"name": e["name"], "ph": "X", "ts": e["ts"], "dur": e["dur"],
         "pid": 0, "tid": e["tid"], "cat": "op"}
        for e in events()
    ]}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


# -- device-side tracing: the jax profiler (XPlane → TensorBoard) replaces
#    the reference's CUPTI DeviceTracer ------------------------------------

def start_trace(log_dir: str):
    import jax

    jax.profiler.start_trace(log_dir)


def stop_trace():
    import jax

    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


# -- per-op device attribution ---------------------------------------------
#
# The jax profiler's trace works through the axon relay (discovered round
# 4 — it is what located the 183 ms attention backward), so the framework
# exposes it as a first-class tool: run a program a few steps under the
# trace and attribute EXCLUSIVE device time to the framework source line
# (= the op lowering) each XLA fusion came from. Reference analog: the
# profiler's per-op device tables + tools/timeline.py.

def _device_events(log_dir: str):
    import glob
    import gzip
    import json as _json

    paths = sorted(glob.glob(
        f"{log_dir}/plugins/profile/*/*.trace.json.gz"))
    if not paths:
        raise RuntimeError(
            f"device_profile: no trace file under {log_dir} — the jax "
            f"profiler produced no dump (trace layout change, or "
            f"start_trace failed)")
    doc = _json.load(gzip.open(paths[-1]))
    ev = doc.get("traceEvents", [])
    dev_pids = {e["pid"] for e in ev
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "/device:" in str(e["args"].get("name"))}
    return [e for e in ev if e.get("ph") == "X" and e["pid"] in dev_pids]


def _exclusive_times(events):
    """Per-event exclusive duration: XLA while/fusion events nest, so a
    parent's time minus its children's is what IT cost."""
    import collections as _c

    by_tid = _c.defaultdict(list)
    for e in events:
        if "dur" in e:
            # tids are process-scoped: key by (pid, tid) or a
            # multi-device trace would interleave devices' timelines
            # into one nesting stack (negative exclusive times)
            by_tid[(e.get("pid"), e.get("tid"))].append(e)
    excl = {}
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in evs:
            while stack and stack[-1]["ts"] + stack[-1]["dur"] <= e["ts"]:
                stack.pop()
            if stack:
                p = stack[-1]
                # only subtract PROPERLY CONTAINED children: a partially
                # overlapping (non-nested) event would otherwise be
                # deducted from the wrong parent, silently skewing the
                # attribution — malformed traces degrade to inclusive
                # times instead (ADVICE r4)
                if e["ts"] + e["dur"] <= p["ts"] + p["dur"]:
                    excl[id(p)] = excl.get(id(p), p["dur"]) - e["dur"]
                else:
                    continue
            stack.append(e)
    return excl


def device_profile(run_step, steps: int = 3, log_dir: Optional[str] = None):
    """Profile `run_step()` (any callable that executes one device step —
    typically a closure over Executor.run) and return rows attributing
    exclusive device time to framework source locations.

    Returns {"ms_per_step": float, "rows": [(source, ms_per_step), ...]}
    sorted by cost. Source is the op lowering's file:line carried by XLA
    metadata; synthetic events (dispatch wrappers) aggregate under their
    event name."""
    import re
    import shutil
    import tempfile

    import collections as _c

    cleanup = log_dir is None
    log_dir = log_dir or tempfile.mkdtemp(prefix="pt_device_profile_")
    try:
        with trace(log_dir):
            for _ in range(steps):
                run_step()
        events = _device_events(log_dir)
    finally:
        if cleanup:
            shutil.rmtree(log_dir, ignore_errors=True)
    excl = _exclusive_times(events)
    by_src = _c.defaultdict(float)
    total = 0.0
    for e in events:
        a = e.get("args") or {}
        name = a.get("long_name") or e.get("name", "")
        if name.startswith("jit_") or re.fullmatch(r"\d+",
                                                   e.get("name", "")):
            continue  # whole-module / step envelope events
        d = excl.get(id(e), e.get("dur", 0))
        src = a.get("source") or e.get("name", "?")[:60]
        by_src[src] += d
        total += d
    rows = sorted(((k, v / 1e3 / steps) for k, v in by_src.items()),
                  key=lambda kv: -kv[1])
    return {"ms_per_step": total / 1e3 / steps, "rows": rows}
