"""Structured runtime telemetry — the unified observability registry.

Capability mirror of the reference's monitoring tier, extended into one
subsystem:

* counters/gauges (platform/monitor.h StatRegistry:77, STAT_ADD:130) —
  absorbed here; ``core.monitor`` keeps ``stat_add``/``stat_get`` as thin
  aliases over this registry;
* histograms/timers for step-time and RPC-latency percentiles (the
  reference reads these off the profiler's summary tables instead);
* a thread-safe JSONL event sink — the persistent per-run record the
  reference gets from CUPTI dumps + tools/timeline.py. Enabled via
  ``FLAGS_telemetry_path`` (or the ``PT_TELEMETRY_LOG`` env var); every
  line is one record of the fixed schema below. ``tools/perf_report.py``
  renders a run log back into tables.

JSONL schema (one object per line)::

    {"ts": <unix seconds>, "kind": <str>, "name": <str>,
     "value": <number|null>, "attrs": {<str>: <json>}}

kinds emitted by the framework: ``counter`` (value = new cumulative,
attrs.delta = increment), ``gauge``, ``timer``/``hist`` (value = sample,
ms for timers), ``compile`` (value = wall ms, attrs.cause = recompile
cause), ``step`` (hapi per-step metrics), ``metric`` (bench results),
``fallback`` (degraded-path latches), ``fault`` (one injected fault from
the core/faults.py harness: name = site, value = per-site injection
count, attrs.exc = raised type — pairs with the ``faults.injected``
counter so chaos runs are auditable), ``snapshot`` (full registry dump at
flush/exit), ``profiler_summary`` (one line per profiler.summarize row).

In-memory aggregation (counters/gauges/histograms) is ALWAYS on — it is
a few dict updates per executor run, invisible next to a device step.
JSONL records are written only when a sink path is configured.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from . import flags as _flags

SCHEMA_FIELDS = ("ts", "kind", "name", "value", "attrs")

_HIST_SAMPLE_CAP = 8192  # per-histogram retained samples (sliding ring)


class _Hist:
    """Running histogram: exact count/sum/min/max + a bounded sample ring
    for percentile estimates (recent-window semantics once full)."""

    __slots__ = ("count", "total", "vmin", "vmax", "samples", "_next")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples = []
        self._next = 0

    def observe(self, v: float):
        v = float(v)
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        if len(self.samples) < _HIST_SAMPLE_CAP:
            self.samples.append(v)
        else:
            self.samples[self._next] = v
            self._next = (self._next + 1) % _HIST_SAMPLE_CAP

    def summary(self) -> Dict[str, float]:
        s = sorted(self.samples)

        def pct(q):
            if not s:
                return 0.0
            return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]

        return {"count": self.count, "total": round(self.total, 3),
                "min": round(self.vmin, 3) if self.count else 0.0,
                "max": round(self.vmax, 3) if self.count else 0.0,
                "avg": round(self.total / self.count, 3) if self.count else 0.0,
                "p50": round(pct(0.50), 3), "p90": round(pct(0.90), 3),
                "p99": round(pct(0.99), 3)}


class TelemetryRegistry:
    _instance: Optional["TelemetryRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Any] = {}
        self._gauges: Dict[str, Any] = {}
        self._hists: Dict[str, _Hist] = {}
        self._file = None
        self._path: Optional[str] = None
        self._sink_warned = False

    @classmethod
    def instance(cls) -> "TelemetryRegistry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    # -- sink ----------------------------------------------------------------
    def _resolve_path(self) -> Optional[str]:
        path = _flags.flag("telemetry_path")
        if not path:
            path = os.environ.get("PT_TELEMETRY_LOG", "")
        return path or None

    def _sink(self):
        """Current sink file (called under self._lock); follows flag/env
        changes so set_flags({'FLAGS_telemetry_path': ...}) takes effect
        mid-run and '' closes the sink."""
        path = self._resolve_path()
        if path != self._path:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = path
            if path:
                try:
                    self._file = open(path, "a", buffering=1)
                except OSError as e:
                    if not self._sink_warned:
                        self._sink_warned = True
                        print(f"[telemetry] cannot open sink {path!r}: {e}",
                              file=sys.stderr)
                    self._path = None
        return self._file

    def enabled(self) -> bool:
        return self._resolve_path() is not None

    def configure(self, path: Optional[str]):
        """Point the JSONL sink at `path` (None/'' disables). Equivalent to
        set_flags({'FLAGS_telemetry_path': path}) — the flag wins over the
        PT_TELEMETRY_LOG env var."""
        _flags.set_flags({"telemetry_path": path or ""})
        with self._lock:
            self._sink()

    def emit(self, kind: str, name: str, value=None,
             attrs: Optional[Dict[str, Any]] = None):
        """Append one schema record to the sink (no-op when disabled)."""
        with self._lock:
            f = self._sink()
            if f is None:
                return
            rec = {"ts": time.time(), "kind": kind, "name": name,
                   "value": value, "attrs": attrs or {}}
            try:
                f.write(json.dumps(rec, default=str) + "\n")
            except (OSError, ValueError, TypeError):
                pass

    # -- metrics -------------------------------------------------------------
    def counter_add(self, name: str, delta=1, **attrs):
        with self._lock:
            val = self._counters.get(name, 0) + delta
            self._counters[name] = val
        self.emit("counter", name, val, {"delta": delta, **attrs})
        return val

    def counter_set(self, name: str, value, **attrs):
        with self._lock:
            self._counters[name] = value
        self.emit("counter", name, value, {"set": True, **attrs})

    def counter_get(self, name: str):
        with self._lock:
            return self._counters.get(name, 0)

    def gauge_set(self, name: str, value, **attrs):
        with self._lock:
            self._gauges[name] = value
        self.emit("gauge", name, value, attrs)

    def observe(self, name: str, value, kind: str = "hist", **attrs):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Hist()
            h.observe(value)
        self.emit(kind, name, round(float(value), 4), attrs)

    @contextlib.contextmanager
    def timer(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, (time.perf_counter() - t0) * 1e3,
                         kind="timer", **attrs)

    # -- snapshots -----------------------------------------------------------
    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._gauges)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "hists": {n: h.summary()
                              for n, h in self._hists.items()}}

    def reset(self):
        """Clear all in-memory aggregates (tests). Leaves the sink alone."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    def flush(self):
        """Persist a full registry snapshot + the profiler's summary table
        into the sink — called atexit so every run log ends with final
        counter values and the host-span rollup perf_report can render."""
        if not self.enabled():
            return
        # gather the profiler summary BEFORE emitting: emit takes this
        # registry's lock per record and profiler.summarize takes the
        # profiler's — never hold both at once (profiler's ring-buffer
        # drop accounting calls back into counter_add)
        prof_rows = {}
        try:
            from .. import profiler as _prof

            prof_rows = _prof.summarize()
        except Exception:
            pass
        self.emit("snapshot", "telemetry", None, self.snapshot())
        for name, row in prof_rows.items():
            self.emit("profiler_summary", name, row.get("total_us"),
                      {k: v for k, v in row.items() if k != "total_us"})


# -- module-level convenience API (the surface everything instruments
#    against; mirrors monitor.h's free-function STAT_ADD style) -------------

def _reg() -> TelemetryRegistry:
    return TelemetryRegistry.instance()


def counter_add(name: str, delta=1, **attrs):
    return _reg().counter_add(name, delta, **attrs)


def counter_set(name: str, value, **attrs):
    return _reg().counter_set(name, value, **attrs)


def counter_get(name: str):
    return _reg().counter_get(name)


def gauge_set(name: str, value, **attrs):
    return _reg().gauge_set(name, value, **attrs)


def observe(name: str, value, kind: str = "hist", **attrs):
    return _reg().observe(name, value, kind=kind, **attrs)


def timer(name: str, **attrs):
    return _reg().timer(name, **attrs)


def event(kind: str, name: str, value=None, attrs=None):
    return _reg().emit(kind, name, value, attrs)


def counters() -> Dict[str, Any]:
    return _reg().counters()


def gauges() -> Dict[str, Any]:
    return _reg().gauges()


def snapshot() -> Dict[str, Any]:
    return _reg().snapshot()


def enabled() -> bool:
    return _reg().enabled()


def configure(path: Optional[str]):
    return _reg().configure(path)


def reset():
    return _reg().reset()


def flush():
    return _reg().flush()


def bench_extra() -> Dict[str, Any]:
    """Key counters for BENCH json `extra` — every BENCH_r*.json carries
    compile/cache/donation accounting from here on (bench.py merges it)."""
    c = counters()
    out = {"telemetry_compiles": int(c.get("executor.compiles", 0)),
           "telemetry_cache_hits": int(c.get("executor.cache_hits", 0)),
           "telemetry_donation_copies":
               int(c.get("executor.donation_copies", 0))}
    # dispatch-amortization accounting (K-step fused execution): how many
    # device steps rode how many host dispatches
    fused_d = int(c.get("executor.fused_dispatches", 0))
    if fused_d:
        out["telemetry_fused_dispatches"] = fused_d
        out["telemetry_fused_steps"] = int(c.get("executor.fused_steps", 0))
    # crash-consistent checkpoint accounting (paddle_tpu/checkpoint.py)
    saves = int(c.get("ckpt.saves", 0))
    if saves:
        out["telemetry_ckpt_saves"] = saves
        out["telemetry_ckpt_bytes"] = int(c.get("ckpt.bytes", 0))
        vf = int(c.get("ckpt.verify_failures", 0))
        if vf:
            out["telemetry_ckpt_verify_failures"] = vf
            out["telemetry_ckpt_fallbacks"] = int(c.get("ckpt.fallbacks", 0))
    # serving-engine accounting (micro-batching runs: bench_serving)
    sreq = int(c.get("serving.requests", 0))
    if sreq:
        out["telemetry_serving_requests"] = sreq
        out["telemetry_serving_batches"] = int(c.get("serving.batches", 0))
        out["telemetry_serving_rejects"] = int(c.get("serving.rejects", 0))
        rows = int(c.get("serving.batched_rows", 0))
        padded = int(c.get("serving.padded_rows", 0))
        if rows:
            out["telemetry_serving_batch_fill"] = round(
                rows / (rows + padded), 4)
    return out


atexit.register(flush)
