"""Structured runtime telemetry — the unified observability registry.

Capability mirror of the reference's monitoring tier, extended into one
subsystem:

* counters/gauges (platform/monitor.h StatRegistry:77, STAT_ADD:130) —
  absorbed here; ``core.monitor`` keeps ``stat_add``/``stat_get`` as thin
  aliases over this registry;
* histograms/timers for step-time and RPC-latency percentiles (the
  reference reads these off the profiler's summary tables instead);
* a thread-safe JSONL event sink — the persistent per-run record the
  reference gets from CUPTI dumps + tools/timeline.py. Enabled via
  ``FLAGS_telemetry_path`` (or the ``PT_TELEMETRY_LOG`` env var); every
  line is one record of the fixed schema below. ``tools/perf_report.py``
  renders a run log back into tables.

JSONL schema (one object per line)::

    {"ts": <unix seconds>, "kind": <str>, "name": <str>,
     "value": <number|null>, "attrs": {<str>: <json>}}

kinds emitted by the framework: ``counter`` (value = new cumulative,
attrs.delta = increment), ``gauge``, ``timer``/``hist`` (value = sample,
ms for timers), ``compile`` (value = wall ms, attrs.cause = recompile
cause), ``step`` (hapi per-step metrics), ``metric`` (bench results),
``fallback`` (degraded-path latches), ``fault`` (one injected fault from
the core/faults.py harness: name = site, value = per-site injection
count, attrs.exc = raised type — pairs with the ``faults.injected``
counter so chaos runs are auditable), ``span`` (one finished distributed-
tracing span from core/trace.py: value = duration ms, attrs = trace/
span/parent ids + start + pid — merged across processes by
tools/trace_view.py), ``incident`` (one anomaly dump from the unified
incident pipeline in core/incidents.py: a tripped SLO watchdog rule or
an OOM/stall/thread-death, bundling the flight-recorder ring + HBM
ledger + active traces — rendered by tools/incident_report.py),
``snapshot`` (full registry dump at flush/exit), ``profiler_summary``
(one line per profiler.summarize row).

In-memory aggregation (counters/gauges/histograms) is ALWAYS on — it is
a few dict updates per executor run, invisible next to a device step.
JSONL records are written only when a sink path is configured; the sink
batches lines in memory and flushes when the buffer reaches
``FLAGS_telemetry_buffer_lines``, every ``FLAGS_telemetry_flush_s``
seconds (a lazy daemon flusher), on ``flush_sink()``/``flush()``, on a
path change, and atexit. Sink write failures NEVER raise into the
instrumented thread — they are counted in ``telemetry.dropped_records``.

Live metrics plane: every counter increment and histogram observation is
also tracked in a rolling window (1-second delta buckets / timestamped
sample rings), so ``windowed()`` yields last-``FLAGS_metrics_window_s``
rates and p50/p95/p99 while the run is live, ``prometheus_text()``
renders them in Prometheus exposition format, and
``start_metrics_server(port)`` serves ``GET /metrics`` from any process
(trainer, pserver, serving worker) — the pull-based scrape surface the
cluster control plane (ROADMAP item 2) load-balances on.

Mergeable histograms: every histogram additionally counts observations
into FIXED log-spaced buckets (``HIST_BUCKET_BOUNDS`` — identical in
every process by construction), exported as cumulative
``pt_<name>_bucket{le="..."}`` series alongside the window summaries.
Bucket counts merge EXACTLY across processes by addition — the fleet
aggregator (core/fleetobs.py) computes fleet-level percentiles from
pooled bucket counts (``merge_bucket_counts`` + ``bucket_quantile``)
instead of the unsound average-of-quantiles.
"""

from __future__ import annotations

import atexit
import bisect
import contextlib
import json
import math
import os
import re
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from . import flags as _flags
from .analysis import lockdep as _lockdep

SCHEMA_FIELDS = ("ts", "kind", "name", "value", "attrs")

# flight-recorder tap (core/incidents.py installs it): every emit()
# record is fed to the hook — an always-on bounded in-memory ring —
# whether or not the JSONL sink is configured. The hook must be cheap
# and must never raise; it is called under the registry lock and uses
# only a plain internal lock, so it cannot create an order cycle.
_blackbox = [None]


def set_blackbox(fn):
    _blackbox[0] = fn

_HIST_SAMPLE_CAP = 8192  # per-histogram retained samples (sliding ring)
_WIN_BUCKET_CAP = 600    # rolling-window 1 s counter buckets (10 min cap)
_WIN_SAMPLE_CAP = 8192   # rolling-window retained histogram samples

#: Fixed log-spaced histogram bucket upper bounds, 4 per decade from
#: 1e-3 to 1e7 (ms-scale timers land mid-range; byte-ish values still
#: fit). The SAME tuple in every process is what makes cross-process
#: bucket counts addable — never derive bounds from runtime state.
HIST_BUCKET_BOUNDS: tuple = tuple(
    round(10.0 ** (i / 4.0) * 1e-3, 9) for i in range(41))


def bucket_index(v: float) -> int:
    """Index of the bucket counting ``v`` (le semantics: first bound
    >= v); len(HIST_BUCKET_BOUNDS) means the +Inf overflow bucket."""
    return bisect.bisect_left(HIST_BUCKET_BOUNDS, float(v))


def merge_bucket_counts(counts_seq: Sequence[Sequence[int]]) -> List[int]:
    """Element-wise sum of per-bucket (NON-cumulative) count vectors —
    the exact cross-registry histogram merge. Short vectors are treated
    as zero-padded (forward compatibility)."""
    out = [0] * (len(HIST_BUCKET_BOUNDS) + 1)
    for counts in counts_seq:
        for i, c in enumerate(counts):
            if i < len(out):
                out[i] += int(c)
    return out


def bucket_quantile(counts: Sequence[int], q: float) -> float:
    """Quantile estimate from per-bucket counts: the UPPER bound of the
    bucket holding the q-th sample (so the true value is within one
    bucket boundary below). Overflow samples clamp to the last finite
    bound — the estimate stays JSON-safe. 0.0 when empty."""
    total = sum(int(c) for c in counts)
    if total <= 0:
        return 0.0
    # same rank rule as the sample-ring percentile: 0-based index
    rank = min(total - 1, int(q * (total - 1) + 0.5))
    cum = 0
    for i, c in enumerate(counts):
        cum += int(c)
        if cum > rank:
            return HIST_BUCKET_BOUNDS[min(i, len(HIST_BUCKET_BOUNDS) - 1)]
    return HIST_BUCKET_BOUNDS[-1]


class _Hist:
    """Running histogram: exact count/sum/min/max + a bounded sample ring
    for percentile estimates (recent-window semantics once full) + fixed
    log-spaced bucket counts (HIST_BUCKET_BOUNDS, exact cross-process
    merge — the pt_*_bucket exposition)."""

    __slots__ = ("count", "total", "vmin", "vmax", "samples", "_next",
                 "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples = []
        self._next = 0
        self.buckets = [0] * (len(HIST_BUCKET_BOUNDS) + 1)

    def observe(self, v: float):
        v = float(v)
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        if math.isfinite(v):
            self.buckets[bisect.bisect_left(HIST_BUCKET_BOUNDS, v)] += 1
        else:
            self.buckets[-1] += 1
        if len(self.samples) < _HIST_SAMPLE_CAP:
            self.samples.append(v)
        else:
            self.samples[self._next] = v
            self._next = (self._next + 1) % _HIST_SAMPLE_CAP

    def summary(self) -> Dict[str, float]:
        s = sorted(self.samples)

        def pct(q):
            if not s:
                return 0.0
            return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]

        return {"count": self.count, "total": round(self.total, 3),
                "min": round(self.vmin, 3) if self.count else 0.0,
                "max": round(self.vmax, 3) if self.count else 0.0,
                "avg": round(self.total / self.count, 3) if self.count else 0.0,
                "p50": round(pct(0.50), 3), "p90": round(pct(0.90), 3),
                "p95": round(pct(0.95), 3), "p99": round(pct(0.99), 3)}


class TelemetryRegistry:
    _instance: Optional["TelemetryRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        # record=False: the sanitizer books its lock metrics THROUGH this
        # registry — the registry's own lock gets order/re-entry/stall
        # detection but must not book about itself
        self._lock = _lockdep.rlock("telemetry.registry", record=False)
        self._counters: Dict[str, Any] = {}
        self._gauges: Dict[str, Any] = {}
        self._hists: Dict[str, _Hist] = {}
        self._file = None
        self._path: Optional[str] = None
        self._sink_warned = False
        # buffered sink: pending JSONL lines + flush bookkeeping
        self._buf: list = []
        self._last_flush = 0.0
        self._flusher_started = False
        # rolling window: per-counter 1 s delta buckets ([sec, sum]) and
        # per-histogram (ts, value) sample rings — pruned lazily on read
        self._win_counts: Dict[str, deque] = {}
        self._win_samples: Dict[str, deque] = {}

    @classmethod
    def instance(cls) -> "TelemetryRegistry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    # -- sink ----------------------------------------------------------------
    def _resolve_path(self) -> Optional[str]:
        path = _flags.flag("telemetry_path")
        if not path:
            path = os.environ.get("PT_TELEMETRY_LOG", "")
        return path or None

    def _sink(self):
        """Current sink file (called under self._lock); follows flag/env
        changes so set_flags({'FLAGS_telemetry_path': ...}) takes effect
        mid-run and '' closes the sink (flushing the buffer into the old
        file first — readers of a just-closed log see every record)."""
        path = self._resolve_path()
        if path != self._path:
            if self._file is not None:
                self._flush_buf_locked()
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._buf.clear()
            self._path = path
            if path:
                try:
                    self._file = open(path, "a")
                    self._last_flush = time.time()
                except OSError as e:
                    if not self._sink_warned:
                        self._sink_warned = True
                        print(f"[telemetry] cannot open sink {path!r}: {e}",
                              file=sys.stderr)
                    self._path = None
        return self._file

    def _drop_locked(self, n: int):
        """Count records lost to a failing sink — in-memory only (a
        counter_add here would recurse into emit)."""
        self._counters["telemetry.dropped_records"] = \
            self._counters.get("telemetry.dropped_records", 0) + n

    def _flush_buf_locked(self):
        """Write the buffered lines as ONE batched write + flush (called
        under self._lock). A failing filesystem must never raise into the
        executor/serving thread that happened to trigger the flush."""
        if not self._buf or self._file is None:
            return
        batch, self._buf = self._buf, []
        self._last_flush = time.time()
        try:
            self._file.write("\n".join(batch) + "\n")
            self._file.flush()
        except (OSError, ValueError):
            self._drop_locked(len(batch))

    def _ensure_flusher_locked(self):
        """Lazy daemon thread: flushes the sink buffer every
        FLAGS_telemetry_flush_s so a mostly-idle process still lands its
        records without waiting for the next emit or exit."""
        if self._flusher_started:
            return
        self._flusher_started = True

        def loop():
            while True:
                try:
                    delay = float(_flags.flag("telemetry_flush_s"))
                except Exception:
                    delay = 0.25
                time.sleep(max(0.05, delay))
                with self._lock:
                    self._flush_buf_locked()

        threading.Thread(target=loop, name="pt-telemetry-flush",
                         daemon=True).start()

    def flush_sink(self):
        """Force the buffered JSONL lines to disk now (tests, scrapes)."""
        with self._lock:
            self._flush_buf_locked()

    def enabled(self) -> bool:
        return self._resolve_path() is not None

    def configure(self, path: Optional[str]):
        """Point the JSONL sink at `path` (None/'' disables). Equivalent to
        set_flags({'FLAGS_telemetry_path': path}) — the flag wins over the
        PT_TELEMETRY_LOG env var."""
        _flags.set_flags({"telemetry_path": path or ""})
        with self._lock:
            self._sink()

    def emit(self, kind: str, name: str, value=None,
             attrs: Optional[Dict[str, Any]] = None):
        """Append one schema record to the sink (no-op when disabled)
        and to the always-on flight-recorder ring (core/incidents.py)
        when one is installed — the ring sees every record even when no
        JSONL sink is configured. Lines are buffered and batch-written
        (see module docstring); any serialisation/write failure is
        counted, never raised."""
        bb = _blackbox[0]
        with self._lock:
            f = self._sink()
            if f is None and bb is None:
                return
            rec = {"ts": time.time(), "kind": kind, "name": name,
                   "value": value, "attrs": attrs or {}}
            if bb is not None:
                try:
                    bb(rec)
                except Exception:
                    pass
            if f is None:
                return
            try:
                self._buf.append(json.dumps(rec, default=str))
            except (ValueError, TypeError):
                self._drop_locked(1)
                return
            try:
                limit = int(_flags.flag("telemetry_buffer_lines"))
            except Exception:
                limit = 1
            if len(self._buf) >= max(1, limit) or \
                    rec["ts"] - self._last_flush >= \
                    float(_flags.flag("telemetry_flush_s")):
                self._flush_buf_locked()
            self._ensure_flusher_locked()

    # -- metrics -------------------------------------------------------------
    def _window_count_locked(self, name: str, delta, now: float):
        """Fold one counter increment into its 1 s rolling-window bucket
        (called under self._lock)."""
        dq = self._win_counts.get(name)
        if dq is None:
            dq = self._win_counts[name] = deque(maxlen=_WIN_BUCKET_CAP)
        sec = int(now)
        if dq and dq[-1][0] == sec:
            dq[-1][1] += delta
        else:
            dq.append([sec, delta])

    def counter_add(self, name: str, delta=1, **attrs):
        with self._lock:
            val = self._counters.get(name, 0) + delta
            self._counters[name] = val
            self._window_count_locked(name, delta, time.time())
        self.emit("counter", name, val, {"delta": delta, **attrs})
        return val

    def counter_quiet(self, name: str, delta=1):
        """In-memory-only increment: no JSONL record. For accounting that
        must not recurse into (or double the volume of) the sink — span
        counts, sink-failure counts."""
        with self._lock:
            val = self._counters.get(name, 0) + delta
            self._counters[name] = val
            self._window_count_locked(name, delta, time.time())
        return val

    def counter_set(self, name: str, value, **attrs):
        with self._lock:
            self._counters[name] = value
        self.emit("counter", name, value, {"set": True, **attrs})

    def counter_get(self, name: str):
        with self._lock:
            return self._counters.get(name, 0)

    def gauge_set(self, name: str, value, **attrs):
        with self._lock:
            self._gauges[name] = value
        self.emit("gauge", name, value, attrs)

    def observe(self, name: str, value, kind: str = "hist", **attrs):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Hist()
            h.observe(value)
            dq = self._win_samples.get(name)
            if dq is None:
                dq = self._win_samples[name] = deque(maxlen=_WIN_SAMPLE_CAP)
            dq.append((time.time(), float(value)))
        self.emit(kind, name, round(float(value), 4), attrs)

    @contextlib.contextmanager
    def timer(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, (time.perf_counter() - t0) * 1e3,
                         kind="timer", **attrs)

    # -- snapshots -----------------------------------------------------------
    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._gauges)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "hists": {n: h.summary()
                              for n, h in self._hists.items()}}

    def hist_buckets(self) -> Dict[str, List[int]]:
        """Per-histogram NON-cumulative bucket counts over
        HIST_BUCKET_BOUNDS (+ overflow slot) — the mergeable view the
        fleet aggregator pools across registries."""
        with self._lock:
            return {n: list(h.buckets) for n, h in self._hists.items()}

    def reset(self):
        """Clear all in-memory aggregates (tests). Leaves the sink alone."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._win_counts.clear()
            self._win_samples.clear()

    # -- rolling-window metrics (the live /metrics plane) --------------------
    def windowed(self, window_s: Optional[float] = None,
                 now: Optional[float] = None) -> Dict[str, Any]:
        """Last-N-seconds view of the registry: counter deltas + per-second
        rates, current gauges, and histogram count/rate/p50/p95/p99 over
        the window (default FLAGS_metrics_window_s). Scrapeable while the
        run is live — this is what /metrics and /v1/stats render.

        ONE cutoff rule for both families: an observation is in the
        window iff its timestamp >= now - W, where a counter bucket's
        timestamp is its second-start (bucket granularity: increments in
        the partial boundary bucket are dropped, never double-counted —
        counters and histogram samples used to disagree by up to a whole
        boundary bucket). ``now`` is injectable for deterministic tests.
        """
        W = float(window_s if window_s is not None
                  else _flags.flag("metrics_window_s"))
        W = max(W, 1.0)
        if now is None:
            now = time.time()
        cut = now - W
        with self._lock:
            counters = {}
            for name, dq in self._win_counts.items():
                tot = 0
                for sec, v in dq:
                    if sec >= cut:
                        tot += v
                if tot:
                    counters[name] = {"delta": tot,
                                      "rate": round(tot / W, 6)}
            hists = {}
            for name, dq in self._win_samples.items():
                vals = sorted(v for ts, v in dq if ts >= cut)
                if not vals:
                    continue
                n = len(vals)

                def pct(q, vals=vals, n=n):
                    return round(vals[min(n - 1, int(q * (n - 1) + 0.5))], 4)

                hists[name] = {"count": n, "rate": round(n / W, 6),
                               "avg": round(sum(vals) / n, 4),
                               "p50": pct(0.50), "p95": pct(0.95),
                               "p99": pct(0.99), "max": round(vals[-1], 4)}
            gauges = dict(self._gauges)
        return {"window_s": W, "ts": now, "counters": counters,
                "gauges": gauges, "hists": hists}

    def prometheus_text(self, window_s: Optional[float] = None) -> str:
        """Prometheus text exposition (0.0.4): cumulative counters as
        ``pt_<name>_total``, rolling-window rates as ``pt_<name>_rate``,
        gauges, histograms as summaries whose quantiles are computed
        over the rolling window (cumulative _sum/_count), plus the
        cumulative fixed-bucket ``pt_<name>_bucket{le="..."}`` series
        (le-ordered, ending with +Inf) the fleet aggregator merges
        exactly."""
        win = self.windowed(window_s)
        W = int(win["window_s"])
        with self._lock:
            cum = {n: v for n, v in self._counters.items()
                   if isinstance(v, (int, float))}
            hist_cum = {n: (h.count, h.total, list(h.buckets))
                        for n, h in self._hists.items()}
        lines = []
        for name in sorted(cum):
            m = _prom_name(name)
            lines.append(f"# TYPE {m}_total counter")
            lines.append(f"{m}_total {_prom_num(cum[name])}")
            wc = win["counters"].get(name)
            if wc is not None:
                lines.append(f"# TYPE {m}_rate gauge")
                lines.append(f'{m}_rate{{window="{W}s"}} '
                             f'{_prom_num(wc["rate"])}')
        for name in sorted(win["gauges"]):
            v = win["gauges"][name]
            if not isinstance(v, (int, float)):
                continue
            lines.append(f"# TYPE {_prom_name(name)} gauge")
            lines.append(f"{_prom_name(name)} {_prom_num(v)}")
        for name in sorted(hist_cum):
            cnt, tot, buckets = hist_cum[name]
            m = _prom_name(name)
            wh = win["hists"].get(name)
            lines.append(f"# TYPE {m} summary")
            if wh:
                for q, key in (("0.5", "p50"), ("0.95", "p95"),
                               ("0.99", "p99")):
                    lines.append(f'{m}{{quantile="{q}"}} '
                                 f'{_prom_num(wh[key])}')
            lines.append(f"{m}_sum {_prom_num(round(tot, 4))}")
            lines.append(f"{m}_count {cnt}")
            # cumulative fixed-bucket series: identical le labels in
            # every process (HIST_BUCKET_BOUNDS), so fleet-side merging
            # is pure addition of counts under matching labels. le must
            # be emitted EXACTLY (repr, not _prom_num's 6-decimal
            # rounding): a rounded-up label maps into the next bucket
            # on the scrape side and misaligns the merge
            running = 0
            for bound, c in zip(HIST_BUCKET_BOUNDS, buckets):
                running += c
                lines.append(f'{m}_bucket{{le="{bound!r}"}} {running}')
            lines.append(f'{m}_bucket{{le="+Inf"}} {cnt}')
            if wh:
                lines.append(f"# TYPE {m}_window_rate gauge")
                lines.append(f'{m}_window_rate{{window="{W}s"}} '
                             f'{_prom_num(wh["rate"])}')
        return "\n".join(lines) + "\n"

    def flush(self):
        """Persist a full registry snapshot + the profiler's summary table
        into the sink — called atexit so every run log ends with final
        counter values and the host-span rollup perf_report can render."""
        if not self.enabled():
            return
        # gather the profiler summary BEFORE emitting: emit takes this
        # registry's lock per record and profiler.summarize takes the
        # profiler's — never hold both at once (profiler's ring-buffer
        # drop accounting calls back into counter_add)
        prof_rows = {}
        try:
            from .. import profiler as _prof

            prof_rows = _prof.summarize()
        except Exception:
            pass
        self.emit("snapshot", "telemetry", None, self.snapshot())
        for name, row in prof_rows.items():
            self.emit("profiler_summary", name, row.get("total_us"),
                      {k: v for k, v in row.items() if k != "total_us"})
        self.flush_sink()


def _prom_name(name: str) -> str:
    return "pt_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _prom_num(v) -> str:
    if isinstance(v, float):
        return repr(round(v, 6))
    return str(v)


# live MetricsServer count: costmodel's 'auto' capture level treats a
# process that started a scrape surface as instrumented
_metrics_servers = 0
_metrics_servers_lock = threading.Lock()


def metrics_server_active() -> bool:
    return _metrics_servers > 0


class MetricsServer:
    """Stdlib HTTP scrape surface over the live registry: ``/metrics``
    (Prometheus text) + ``/healthz``. Started by start_metrics_server —
    usable from trainers and pservers, and mirrored by the serving
    server's own /metrics route."""

    def __init__(self, registry: "TelemetryRegistry",
                 host: str = "127.0.0.1", port: int = 0):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        reg = registry

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(200, reg.prometheus_text().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    self._send(200, b'{"status": "ok"}',
                               "application/json")
                elif path == "/varz":
                    body = json.dumps({"snapshot": reg.snapshot(),
                                       "window": reg.windowed()},
                                      default=str).encode()
                    self._send(200, body, "application/json")
                else:
                    self._send(404, b'{"error": "no route"}',
                               "application/json")

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pt-metrics-http", daemon=True)
        self._thread.start()
        global _metrics_servers
        with _metrics_servers_lock:
            _metrics_servers += 1
        # a scrape surface marks the run as instrumented: arm the SLO
        # watchdog plane (core/incidents.py, FLAGS_slo_watchdog 'auto')
        try:
            from . import incidents

            incidents.arm()
        except Exception:
            pass

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        global _metrics_servers
        with _metrics_servers_lock:
            _metrics_servers = max(0, _metrics_servers - 1)
        try:
            from . import incidents

            incidents.disarm()
        except Exception:
            pass


# -- module-level convenience API (the surface everything instruments
#    against; mirrors monitor.h's free-function STAT_ADD style) -------------

def _reg() -> TelemetryRegistry:
    return TelemetryRegistry.instance()


def counter_add(name: str, delta=1, **attrs):
    return _reg().counter_add(name, delta, **attrs)


def counter_set(name: str, value, **attrs):
    return _reg().counter_set(name, value, **attrs)


def counter_get(name: str):
    return _reg().counter_get(name)


def counter_quiet(name: str, delta=1):
    return _reg().counter_quiet(name, delta)


def gauge_set(name: str, value, **attrs):
    return _reg().gauge_set(name, value, **attrs)


def observe(name: str, value, kind: str = "hist", **attrs):
    return _reg().observe(name, value, kind=kind, **attrs)


def timer(name: str, **attrs):
    return _reg().timer(name, **attrs)


def event(kind: str, name: str, value=None, attrs=None):
    return _reg().emit(kind, name, value, attrs)


def counters() -> Dict[str, Any]:
    return _reg().counters()


def gauges() -> Dict[str, Any]:
    return _reg().gauges()


def snapshot() -> Dict[str, Any]:
    return _reg().snapshot()


def hist_buckets() -> Dict[str, List[int]]:
    return _reg().hist_buckets()


def enabled() -> bool:
    return _reg().enabled()


def configure(path: Optional[str]):
    return _reg().configure(path)


def reset():
    return _reg().reset()


def flush():
    return _reg().flush()


def flush_sink():
    return _reg().flush_sink()


def windowed(window_s: Optional[float] = None,
             now: Optional[float] = None) -> Dict[str, Any]:
    return _reg().windowed(window_s, now=now)


def prometheus_text(window_s: Optional[float] = None) -> str:
    return _reg().prometheus_text(window_s)


def start_metrics_server(port: int = 0,
                         host: str = "127.0.0.1") -> MetricsServer:
    """Serve GET /metrics (Prometheus text) + /healthz + /varz from this
    process's live registry on ``host:port`` (port 0 = ephemeral).
    Returns the started MetricsServer (``.url``, ``.shutdown()``)."""
    return MetricsServer(_reg(), host=host, port=port)


def bench_extra() -> Dict[str, Any]:
    """Key counters for BENCH json `extra` — every BENCH_r*.json carries
    compile/cache/donation accounting from here on (bench.py merges it)."""
    c = counters()
    out = {"telemetry_compiles": int(c.get("executor.compiles", 0)),
           "telemetry_cache_hits": int(c.get("executor.cache_hits", 0)),
           "telemetry_donation_copies":
               int(c.get("executor.donation_copies", 0))}
    # dispatch-amortization accounting (K-step fused execution): how many
    # device steps rode how many host dispatches
    fused_d = int(c.get("executor.fused_dispatches", 0))
    if fused_d:
        out["telemetry_fused_dispatches"] = fused_d
        out["telemetry_fused_steps"] = int(c.get("executor.fused_steps", 0))
    # crash-consistent checkpoint accounting (paddle_tpu/checkpoint.py)
    saves = int(c.get("ckpt.saves", 0))
    if saves:
        out["telemetry_ckpt_saves"] = saves
        out["telemetry_ckpt_bytes"] = int(c.get("ckpt.bytes", 0))
        vf = int(c.get("ckpt.verify_failures", 0))
        if vf:
            out["telemetry_ckpt_verify_failures"] = vf
            out["telemetry_ckpt_fallbacks"] = int(c.get("ckpt.fallbacks", 0))
    # serving-engine accounting (micro-batching runs: bench_serving)
    sreq = int(c.get("serving.requests", 0))
    if sreq:
        out["telemetry_serving_requests"] = sreq
        out["telemetry_serving_batches"] = int(c.get("serving.batches", 0))
        out["telemetry_serving_rejects"] = int(c.get("serving.rejects", 0))
        rows = int(c.get("serving.batched_rows", 0))
        padded = int(c.get("serving.padded_rows", 0))
        if rows:
            out["telemetry_serving_batch_fill"] = round(
                rows / (rows + padded), 4)
    return out


atexit.register(flush)
