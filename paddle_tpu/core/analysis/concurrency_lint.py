"""Static concurrency lint — the AST twin of the runtime lock sanitizer.

Lints the ``paddle_tpu/`` + ``tools/`` sources (stdlib-only: ast + re,
no jax import — runs anywhere, like tools/perf_report.py) for the
concurrency defects that become 3 a.m. stalls:

* ``lock-order`` (error) — per module, every ``with <lock>:`` nesting
  (and every call made under a held lock, expanded transitively through
  same-module/same-class callees) contributes an edge to a lock-
  acquisition graph; a cycle is a potential A/B–B/A deadlock and every
  edge inside the cycle is reported with its ``file:line``;
* ``blocking-call-under-lock`` (warning) — socket/HTTP operations,
  ``subprocess`` launches, ``time.sleep``, queue ``get``/``put`` and
  bare ``.wait()``/``.join()`` without timeouts, and jit/compile entry
  points (``predictor.run``, ``jax.jit``) executed while a lock is
  held, including through one same-module call chain;
* ``unlocked-shared-field`` (warning) — a ``self.<attr>`` written both
  from a thread-entrypoint path (``Thread(target=self.m)`` targets and
  their same-class callees, plus ``do_*`` handler methods of
  *Handler classes) and from the main path, where at least one write
  holds no lock (``__init__`` writes are construction-time and exempt);
* ``thread-unnamed`` (error) / ``thread-unjoined`` (warning) — every
  ``threading.Thread(...)`` spawn must carry ``name=`` (the
  ``pt-<subsystem>-<role>`` convention the stall dumps and excepthook
  records key on) and must either be a daemon or be joined with a
  bounded timeout.

Findings carry ``file:line`` + severity. Inline suppression::

    something_risky()   # pt-lint: disable=<rule>(reason)

on the finding line or the line above; multiple rules comma-separate.
A suppressed finding is counted but does not fail the lint. CLI:
``tools/lint_concurrency.py`` (exit 0 clean / 1 findings / 2 unloadable
source, like tools/graph_lint.py).

This is a heuristic source lint, not a soundness proof: lock identity is
name-based (``ClassName.attr`` for ``self.*`` locks, module-qualified
otherwise), call expansion stays within one module, and two instances of
the same class share a lock name (same-name edges are skipped, exactly
like the runtime graph). The runtime half (lockdep.py) covers what the
static half cannot see.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Any, Dict, List, Optional, Set, Tuple

RULES: Dict[str, str] = {
    "lock-order": "error",
    "blocking-call-under-lock": "warning",
    "unlocked-shared-field": "warning",
    "thread-unnamed": "error",
    "thread-unjoined": "warning",
}

_LOCKISH = re.compile(r"lock$|mutex$|cond$|cv$|condition$", re.I)
_QUEUEISH = re.compile(r"(?:^|_)q(?:ueue)?$", re.I)
_SUPPRESS = re.compile(
    r"#\s*pt-lint:\s*disable=([a-z0-9_\-,\s]+?)\s*(?:\((.*)\))?\s*$")
_BLOCK_SUBPROCESS = {"run", "Popen", "check_call", "check_output", "call"}
_BLOCK_SOCKET_METHODS = {"recv", "recv_into", "accept", "sendall",
                         "getresponse", "create_connection", "urlopen"}


class Finding:
    __slots__ = ("rule", "severity", "path", "line", "message",
                 "suppressed")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.severity = RULES[rule]
        self.path = path
        self.line = int(line)
        self.message = message
        self.suppressed: Optional[str] = None   # suppression reason

    def format(self) -> str:
        sup = f"  [suppressed: {self.suppressed}]" \
            if self.suppressed is not None else ""
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"{self.rule}: {self.message}{sup}")

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line,
                "message": self.message, "suppressed": self.suppressed}


class LintResult:
    def __init__(self):
        self.findings: List[Finding] = []     # unsuppressed
        self.suppressed: List[Finding] = []
        self.files = 0
        self.parse_errors: List[Tuple[str, str]] = []

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]


# ---------------------------------------------------------------------------
# per-function collection
# ---------------------------------------------------------------------------

def _chain(node) -> Optional[str]:
    """Dotted text of a Name/Attribute chain ('self._lock',
    'telemetry.counter_add'); None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FuncInfo:
    def __init__(self, qualname: str, class_name: Optional[str]):
        self.qualname = qualname
        self.class_name = class_name
        # (lock_id, line, held_names_tuple)
        self.acquisitions: List[Tuple[str, int, tuple]] = []
        # (callee_key, display, line, held_names_tuple)
        self.calls: List[Tuple[tuple, str, int, tuple]] = []
        # (description, line, held_names_tuple)
        self.blocking: List[Tuple[str, int, tuple]] = []
        # (attr, line, locked)
        self.self_stores: List[Tuple[str, int, bool]] = []


class _ThreadSpawn:
    def __init__(self, line: int, func: "_FuncInfo"):
        self.line = line
        self.func = func
        self.has_name = False
        self.daemon = False
        self.assigned_to: Optional[str] = None   # last segment of target
        self.assigned_self = False               # target was self.<attr>
        self.target_method: Optional[str] = None  # self.X target
        self.target_func: Optional[str] = None    # bare-name target


class _ModuleLint:
    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.modname = os.path.splitext(os.path.basename(path))[0]
        self.tree = tree
        self.lines = source.splitlines()
        self.functions: Dict[str, _FuncInfo] = {}
        self.methods: Dict[Tuple[str, str], _FuncInfo] = {}
        self.spawns: List[_ThreadSpawn] = []
        # (receiver_last_segment, bounded, enclosing_qualname)
        self.joins: List[Tuple[str, bool, str]] = []
        self.daemon_sets: Set[str] = set()   # `x.daemon = True` receivers
        self.handler_classes: Set[str] = set()
        self.class_methods: Dict[str, Set[str]] = {}
        self.findings: List[Finding] = []

    # -- identity helpers ----------------------------------------------------
    def lock_id(self, expr, class_name: Optional[str]) -> Optional[str]:
        chain = _chain(expr)
        if chain is None:
            return None
        last = chain.rsplit(".", 1)[-1]
        if not _LOCKISH.search(last):
            return None
        if chain.startswith("self."):
            rest = chain[len("self."):]
            return f"{class_name}.{rest}" if class_name else rest
        if "." not in chain:
            return f"{self.modname}.{chain}"
        return f"{self.modname}:{chain}"

    # -- collection ----------------------------------------------------------
    def collect(self):
        # module-level statements run too (scripts, __main__ blocks):
        # walk them as a pseudo-function so module-level spawns/withs
        # are linted like any other code
        top = _FuncInfo("<module>", None)
        self.functions["<module>"] = top
        toplevel = [n for n in self.tree.body
                    if not isinstance(n, (ast.ClassDef, ast.FunctionDef,
                                          ast.AsyncFunctionDef))]
        self._walk(toplevel, top, [], None, "<module>")
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                bases = [(_chain(b) or "") for b in node.bases]
                if any(base.rsplit(".", 1)[-1].endswith("Handler")
                       for base in bases):
                    self.handler_classes.add(node.name)
                self.class_methods[node.name] = set()
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.class_methods[node.name].add(sub.name)
                        self._collect_function(sub, node.name,
                                               f"{node.name}.{sub.name}")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_function(node, None, node.name)

    def _collect_function(self, node, class_name, qualname):
        info = _FuncInfo(qualname, class_name)
        self.functions[qualname] = info
        if class_name:
            self.methods[(class_name, node.name)] = info
        self._walk(node.body, info, [], class_name, qualname)

    def _walk(self, stmts, info: _FuncInfo, held: List[str],
              class_name, qualname):
        for stmt in stmts:
            self._walk_stmt(stmt, info, held, class_name, qualname)

    def _walk_stmt(self, node, info, held, class_name, qualname):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def is its own function (runs when CALLED, not
            # where defined) — empty held stack of its own
            nested = f"{qualname}.{node.name}"
            sub = _FuncInfo(nested, class_name)
            self.functions[nested] = sub
            # callable by bare name from the enclosing scope
            self.functions.setdefault(node.name, sub)
            self._walk(node.body, sub, [], class_name, nested)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = []
            for item in node.items:
                lid = self.lock_id(item.context_expr, class_name)
                if lid is not None:
                    info.acquisitions.append(
                        (lid, item.context_expr.lineno, tuple(held)))
                    held.append(lid)
                    pushed.append(lid)
                else:
                    self._scan_expr(item.context_expr, info, held,
                                    class_name)
            self._walk(node.body, info, held, class_name, qualname)
            for _ in pushed:
                held.pop()
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = list(node.targets) if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in list(targets):
                if isinstance(tgt, (ast.Tuple, ast.List)):
                    targets.extend(tgt.elts)
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and class_name:
                    info.self_stores.append(
                        (tgt.attr, tgt.lineno, bool(held)))
                # `x.daemon = True`
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "daemon" and \
                        isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value is True:
                    recv = _chain(tgt.value)
                    if recv:
                        self.daemon_sets.add(recv.rsplit(".", 1)[-1])
            value = getattr(node, "value", None)
            if value is not None:
                spawn = self._thread_spawn_of(value, info)
                if spawn is not None:
                    for tgt in targets:
                        tchain = _chain(tgt)
                        if tchain:
                            spawn.assigned_to = tchain.rsplit(".", 1)[-1]
                            spawn.assigned_self = \
                                tchain.startswith("self.")
                self._scan_expr(value, info, held, class_name)
            return
        # generic: scan this statement's expressions, recurse into bodies
        for field in ("test", "iter", "value", "exc", "cause"):
            sub = getattr(node, field, None)
            if isinstance(sub, ast.expr):
                self._scan_expr(sub, info, held, class_name)
        for field in ("body", "orelse", "finalbody"):
            body = getattr(node, field, None)
            if isinstance(body, list):
                self._walk(body, info, held, class_name, qualname)
        for handler in getattr(node, "handlers", []) or []:
            self._walk(handler.body, info, held, class_name, qualname)

    def _scan_expr(self, expr, info, held, class_name):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_call(node, info, held, class_name)

    # -- call classification -------------------------------------------------
    def _thread_spawn_of(self, expr, info) -> Optional[_ThreadSpawn]:
        """A threading.Thread(...) / Thread(...) construction (also when
        wrapped as `Thread(...).start()` or inside a comprehension)."""
        calls = [n for n in ast.walk(expr) if isinstance(n, ast.Call)]
        for call in calls:
            chain = _chain(call.func) or ""
            if chain in ("threading.Thread", "Thread"):
                spawn = _ThreadSpawn(call.lineno, info)
                for kw in call.keywords:
                    if kw.arg == "name":
                        spawn.has_name = True
                    elif kw.arg == "daemon" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value is True:
                        spawn.daemon = True
                    elif kw.arg == "target":
                        tchain = _chain(kw.value) or ""
                        if tchain.startswith("self."):
                            spawn.target_method = tchain[len("self."):]
                        elif tchain and "." not in tchain:
                            spawn.target_func = tchain
                self.spawns.append(spawn)
                return spawn
        return None

    def _scan_call(self, call: ast.Call, info: _FuncInfo, held, class_name):
        chain = _chain(call.func)
        if chain in ("threading.Thread", "Thread"):
            if not any(s.line == call.lineno for s in self.spawns):
                self._thread_spawn_of(call, info)
            return
        if chain is None:
            return
        parts = chain.split(".")
        last = parts[-1]
        recv = ".".join(parts[:-1])
        recv_last = parts[-2] if len(parts) > 1 else ""
        kwargs = {kw.arg for kw in call.keywords if kw.arg}
        npos = len(call.args)
        line = call.lineno
        held_t = tuple(held)

        # explicit lock.acquire() counts as an acquisition edge
        if last == "acquire" and recv:
            lid = self.lock_id(call.func.value, class_name)
            if lid is not None:
                info.acquisitions.append((lid, line, held_t))
                return

        # joins feed the thread-lifecycle rule
        if last == "join" and recv:
            bounded = npos >= 1 or "timeout" in kwargs
            self.joins.append((recv_last, bounded, info.qualname))

        # resolvable same-module calls (for lock/blocking expansion)
        if chain.startswith("self.") and len(parts) == 2 and class_name:
            info.calls.append((("m", class_name, last), chain, line,
                               held_t))
        elif len(parts) == 1:
            info.calls.append((("f", last), chain, line, held_t))

        # direct blocking operations
        desc = self._blocking_desc(chain, parts, last, recv, recv_last,
                                   kwargs, npos, held)
        if desc is not None:
            info.blocking.append((desc, line, held_t))

    def _blocking_desc(self, chain, parts, last, recv, recv_last,
                       kwargs, npos, held) -> Optional[str]:
        if chain == "time.sleep":
            return "time.sleep()"
        if parts[0] == "subprocess" and last in _BLOCK_SUBPROCESS:
            return f"subprocess.{last}()"
        if last in _BLOCK_SOCKET_METHODS:
            return f"socket/HTTP operation .{last}()"
        if last in ("get", "put") and "timeout" not in kwargs and \
                _QUEUEISH.search(recv_last or ""):
            if last == "get" and npos > 0:
                return None   # dict-style get(key)
            return f"queue .{last}() without timeout"
        if last == "join" and recv and npos == 0 and \
                "timeout" not in kwargs:
            return "unbounded .join()"
        if last in ("wait", "wait_for") and "timeout" not in kwargs and \
                (npos == 0 if last == "wait" else npos <= 1):
            # waiting on the condition/lock you hold is how Conditions
            # work; any OTHER unbounded wait under a lock is a stall seed
            if recv and _LOCKISH.search(recv_last or ""):
                return None
            return f"unbounded .{last}()"
        if chain == "jax.jit" or \
                (last == "run" and "predictor" in (recv or "").lower()):
            return f"jit/compile entry point {chain}()"
        return None


# ---------------------------------------------------------------------------
# module-level rules
# ---------------------------------------------------------------------------

def _lock_footprints(mod: _ModuleLint) -> Dict[str, Set[str]]:
    """Transitive per-function lock-acquisition sets (same-module call
    resolution, cycle-safe)."""
    memo: Dict[str, Set[str]] = {}
    visiting: Set[str] = set()

    def resolve(key) -> Optional[_FuncInfo]:
        if key[0] == "m":
            return mod.methods.get((key[1], key[2]))
        return mod.functions.get(key[1])

    def fp(name: str) -> Set[str]:
        if name in memo:
            return memo[name]
        if name in visiting:
            return set()
        visiting.add(name)
        info = mod.functions[name]
        out = {lid for lid, _, _ in info.acquisitions}
        for key, _disp, _line, _held in info.calls:
            callee = resolve(key)
            if callee is not None and callee.qualname in mod.functions:
                out |= fp(callee.qualname)
        visiting.discard(name)
        memo[name] = out
        return out

    for name in mod.functions:
        fp(name)
    return memo


def _blocking_surfaces(mod: _ModuleLint) -> Dict[str, List[Tuple[str, int]]]:
    """Transitive blocking operations reachable from a function's entry
    with NO lock held inside it (i.e. what a caller inherits)."""
    memo: Dict[str, List[Tuple[str, int]]] = {}
    visiting: Set[str] = set()

    def resolve(key) -> Optional[_FuncInfo]:
        if key[0] == "m":
            return mod.methods.get((key[1], key[2]))
        return mod.functions.get(key[1])

    def surface(name: str) -> List[Tuple[str, int]]:
        if name in memo:
            return memo[name]
        if name in visiting:
            return []
        visiting.add(name)
        info = mod.functions[name]
        out = [(desc, line) for desc, line, held in info.blocking
               if not held]
        for key, disp, line, held in info.calls:
            if held:
                continue
            callee = resolve(key)
            if callee is not None:
                for desc, bline in surface(callee.qualname):
                    out.append((f"{desc} (via {disp}:{bline})", line))
        visiting.discard(name)
        memo[name] = out[:8]
        return memo[name]

    for name in mod.functions:
        surface(name)
    return memo


def _rule_lock_order(mod: _ModuleLint):
    footprints = _lock_footprints(mod)
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[int, str]] = {}

    def resolve(key) -> Optional[_FuncInfo]:
        if key[0] == "m":
            return mod.methods.get((key[1], key[2]))
        return mod.functions.get(key[1])

    def add(a: str, b: str, line: int, why: str):
        if a == b:
            return   # same name = same instance or a sibling; skip
        edges.setdefault(a, set()).add(b)
        sites.setdefault((a, b), (line, why))

    for info in mod.functions.values():
        for lid, line, held in info.acquisitions:
            for h in dict.fromkeys(held):
                add(h, lid, line, f"'{lid}' acquired directly")
        for key, disp, line, held in info.calls:
            if not held:
                continue
            callee = resolve(key)
            if callee is None:
                continue
            for lid in footprints.get(callee.qualname, ()):
                for h in dict.fromkeys(held):
                    add(h, lid, line, f"'{lid}' acquired inside {disp}()")

    # strongly connected components (iterative Tarjan)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0: str):
        work = [(v0, iter(sorted(edges.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        onstack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                elif w in onstack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(scc)

    for v in sorted(edges):
        if v not in index:
            strongconnect(v)

    for scc in sccs:
        members = set(scc)
        cyc = " <-> ".join(sorted(members))
        for (a, b), (line, why) in sorted(sites.items(),
                                          key=lambda kv: kv[1][0]):
            if a in members and b in members:
                mod.findings.append(Finding(
                    "lock-order", mod.path, line,
                    f"acquiring '{b}' while holding '{a}' closes a "
                    f"lock-order cycle [{cyc}] — potential A/B-B/A "
                    f"deadlock ({why})"))


def _rule_blocking(mod: _ModuleLint):
    surfaces = _blocking_surfaces(mod)

    def resolve(key) -> Optional[_FuncInfo]:
        if key[0] == "m":
            return mod.methods.get((key[1], key[2]))
        return mod.functions.get(key[1])

    for info in mod.functions.values():
        for desc, line, held in info.blocking:
            if held:
                mod.findings.append(Finding(
                    "blocking-call-under-lock", mod.path, line,
                    f"{desc} while holding lock '{held[-1]}' — a slow "
                    f"peer stalls every thread contending on it"))
        for key, disp, line, held in info.calls:
            if not held:
                continue
            callee = resolve(key)
            if callee is None:
                continue
            surf = surfaces.get(callee.qualname) or []
            if surf:
                desc, bline = surf[0]
                mod.findings.append(Finding(
                    "blocking-call-under-lock", mod.path, line,
                    f"call to {disp}() performs {desc} while lock "
                    f"'{held[-1]}' is held"))


def _rule_unlocked_fields(mod: _ModuleLint):
    # entrypoints: Thread(target=self.m) targets anywhere in the class,
    # plus do_* methods of *Handler subclasses (server worker threads)
    entry_by_class: Dict[str, Set[str]] = {}
    for spawn in mod.spawns:
        if spawn.target_method and spawn.func.class_name:
            entry_by_class.setdefault(spawn.func.class_name, set()).add(
                spawn.target_method)
    for cls in mod.handler_classes:
        for m in mod.class_methods.get(cls, ()):
            if m.startswith("do_"):
                entry_by_class.setdefault(cls, set()).add(m)

    for cls, entries in entry_by_class.items():
        methods = mod.class_methods.get(cls, set())
        # close each entrypoint over its same-class callees
        reach: Set[str] = set()
        frontier = [m for m in entries if m in methods]
        while frontier:
            m = frontier.pop()
            if m in reach:
                continue
            reach.add(m)
            info = mod.methods.get((cls, m))
            if info is None:
                continue
            for key, _disp, _line, _held in info.calls:
                if key[0] == "m" and key[1] == cls and key[2] in methods:
                    frontier.append(key[2])
        # collect per-attr write contexts
        writes: Dict[str, List[Tuple[str, int, bool, str]]] = {}
        for m in methods:
            if m == "__init__":
                continue
            info = mod.methods.get((cls, m))
            if info is None:
                continue
            ctx = "worker" if m in reach else "main"
            for attr, line, locked in info.self_stores:
                writes.setdefault(attr, []).append((ctx, line, locked, m))
        for attr, sites in writes.items():
            ctxs = {c for c, _, _, _ in sites}
            unlocked = [(line, m) for _c, line, locked, m in sites
                        if not locked]
            if len(ctxs) >= 2 and unlocked:
                for line, m in unlocked:
                    mod.findings.append(Finding(
                        "unlocked-shared-field", mod.path, line,
                        f"'self.{attr}' is written from a thread "
                        f"entrypoint path and from the main path, but "
                        f"this write in {cls}.{m}() holds no lock — "
                        f"torn/lost update under concurrency"))


def _rule_thread_lifecycle(mod: _ModuleLint):
    for spawn in mod.spawns:
        if not spawn.has_name:
            mod.findings.append(Finding(
                "thread-unnamed", mod.path, spawn.line,
                "threading.Thread(...) without name= — stall dumps, "
                "excepthook records and ps/top views need the "
                "'pt-<subsystem>-<role>' name"))
        daemon = spawn.daemon or (
            spawn.assigned_to is not None and
            spawn.assigned_to in mod.daemon_sets)
        if daemon:
            continue
        joined = False
        for recv_last, bounded, qual in mod.joins:
            if not bounded:
                continue
            if qual == spawn.func.qualname:
                joined = True   # bounded join in the same function body
                break
            # a thread stored on self is typically joined from another
            # method (start()/close() pairs) — match by attribute name
            if spawn.assigned_self and recv_last == spawn.assigned_to:
                joined = True
                break
        if not joined:
            mod.findings.append(Finding(
                "thread-unjoined", mod.path, spawn.line,
                "non-daemon thread is never joined with a bounded "
                "timeout — a wedged worker blocks interpreter exit "
                "forever (pass daemon=True or join(timeout=...))"))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _apply_suppressions(mod: _ModuleLint):
    sup: Dict[int, List[Tuple[Set[str], str]]] = {}
    for i, line in enumerate(mod.lines, 1):
        m = _SUPPRESS.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            sup.setdefault(i, []).append((rules, m.group(2) or ""))
    for f in mod.findings:
        for ln in (f.line, f.line - 1):
            for rules, reason in sup.get(ln, ()):
                if f.rule in rules or "all" in rules:
                    f.suppressed = reason or "no reason given"
                    break
            if f.suppressed is not None:
                break


def lint_file(path: str, result: LintResult):
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        result.parse_errors.append((path, f"{type(e).__name__}: {e}"))
        return
    result.files += 1
    mod = _ModuleLint(path, source, tree)
    mod.collect()
    _rule_lock_order(mod)
    _rule_blocking(mod)
    _rule_unlocked_fields(mod)
    _rule_thread_lifecycle(mod)
    _apply_suppressions(mod)
    mod.findings.sort(key=lambda f: (f.line, f.rule))
    for f in mod.findings:
        (result.suppressed if f.suppressed is not None
         else result.findings).append(f)


def iter_sources(roots: List[str]) -> List[str]:
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def lint_paths(paths: List[str]) -> LintResult:
    result = LintResult()
    for path in iter_sources(paths):
        lint_file(path, result)
    result.findings.sort(key=lambda f: (f.path, f.line))
    result.suppressed.sort(key=lambda f: (f.path, f.line))
    return result


def default_roots() -> List[str]:
    """The lint scope from the repo root: framework + tools sources
    (tests spawn scratch threads on purpose and are out of scope)."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    return [os.path.join(here, "paddle_tpu"), os.path.join(here, "tools")]
