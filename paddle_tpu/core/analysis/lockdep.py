"""Runtime lock sanitizer — lockdep/TSan discipline for the threaded
runtime.

PRs 4-10 made paddle_tpu a heavily threaded system (serving engine +
router + cluster supervisor, PS RPC server threads, async checkpoint
writer, telemetry flusher, heartbeat monitors). Every deadlock-freedom
property was proven only dynamically by chaos tests; this module makes
the two classic failure modes *detectable in process*, the way the Linux
kernel's lockdep does:

* **lock-order cycles** — every instrumented acquire records the edge
  (each currently-held lock name) -> (acquired lock name) in one global
  acquisition-order graph. An acquire that would close a cycle (thread 1
  takes A then B while thread 2 takes B then A) raises a typed
  :class:`LockOrderError` *before blocking* — the potential deadlock is
  reported the first time the inverted order is even attempted, whether
  or not the schedule actually wedged;
* **same-thread re-entry** — re-acquiring a non-reentrant lock the
  current thread already holds is a guaranteed self-deadlock; it raises
  :class:`LockOrderError` immediately instead of hanging;
* **stall watchdog** — a daemon thread watches every in-flight
  instrumented acquire; one that has been waiting longer than
  ``FLAGS_lock_stall_s`` produces a ``kind:"stall"`` run-log record with
  ALL thread stacks (named threads, held/waited locks) — the 3 a.m.
  wedged-router forensics, captured while the process is still wedged;
* **contention accounting** — ``lock.acquires`` / ``lock.contentions``
  counters and per-lock ``lock.<name>.held_ms`` / ``lock.<name>.wait_ms``
  timers, rendered by tools/perf_report.py's "Concurrency" section.

Cost discipline (same as core/costmodel.py): everything is behind
``FLAGS_sanitize_locks``, default off. The factories below return PLAIN
``threading`` primitives when the flag is off — zero wrapper, zero
records, bit-identical lock behavior. The flag is read at *construction*
time, so enabling it mid-process instruments locks created afterwards
(tests construct their engines/routers under the flag; module-level
locks pick it up via the FLAGS_sanitize_locks env var at import).

Static twin: tools/lint_concurrency.py runs the same discipline over the
SOURCES (core/analysis/concurrency_lint.py) — lock-order inversions,
blocking calls under locks and unguarded shared fields become lint
failures before they become runtime stalls.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Set

from .. import flags as _flags


class LockOrderError(RuntimeError):
    """A lock acquisition that is a (potential) deadlock: either it
    closes a cycle in the global acquisition-order graph, or it re-enters
    a non-reentrant lock the same thread already holds."""


def enabled() -> bool:
    return bool(_flags.flag("sanitize_locks"))


# -- global sanitizer state ---------------------------------------------------
# _state_lock is a PLAIN lock guarding the order graph + waiter table; it
# is never held while blocking on an instrumented lock or calling out
# into telemetry, so it cannot itself participate in a deadlock.
_state_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}            # name -> names acquired under it
_waiters: Dict[int, Dict[str, Any]] = {}    # thread ident -> waiting info
_held_by_thread: Dict[int, List[Dict[str, Any]]] = {}   # diagnostics mirror
_watchdog_started = False

_tls = threading.local()


def _held() -> List[Dict[str, Any]]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
        _held_by_thread[threading.get_ident()] = held
    return held


def _booking() -> bool:
    return bool(getattr(_tls, "booking", False))


def _book(fn, *args, **kwargs):
    """Run one telemetry call with the re-entrancy guard set: telemetry's
    own (instrumented) registry lock must not recurse back into
    order-recording/booking from inside a booking call."""
    _tls.booking = True
    try:
        fn(*args, **kwargs)
    except Exception:
        pass
    finally:
        _tls.booking = False


def _telemetry():
    from .. import telemetry

    return telemetry


def _reachable(src: str, dst: str) -> Optional[List[str]]:
    """Path src ->* dst in the order graph (caller holds _state_lock);
    returns the node path or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def reset_order_graph():
    """Drop every recorded acquisition-order edge (tests)."""
    with _state_lock:
        _edges.clear()


def _ensure_watchdog():
    global _watchdog_started
    with _state_lock:
        if _watchdog_started:
            return
        _watchdog_started = True
    threading.Thread(target=_watchdog_loop, name="pt-lockdep-watchdog",
                     daemon=True).start()


def _watchdog_loop():
    """Scan the waiter table; any instrumented acquire stalled past
    FLAGS_lock_stall_s gets ONE all-thread stack dump (kind:"stall")."""
    while True:
        try:
            stall_s = float(_flags.flag("lock_stall_s"))
        except Exception:
            stall_s = 30.0
        time.sleep(max(min(stall_s / 4.0, 0.5), 0.02))
        now = time.monotonic()
        dumps = []
        with _state_lock:
            for ident, w in _waiters.items():
                if not w.get("dumped") and now - w["t0"] >= stall_s:
                    w["dumped"] = True
                    dumps.append((ident, dict(w)))
        for ident, w in dumps:
            _dump_stall(ident, w, now - w["t0"])


def _thread_table() -> Dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate()
            if t.ident is not None}


def _dump_stall(ident: int, waiter: Dict[str, Any], waited_s: float):
    """One stalled acquire -> one kind:"stall" record: every live
    thread's name, held locks, waited lock and stack."""
    names = _thread_table()
    with _state_lock:
        waiting = {tid: dict(w) for tid, w in _waiters.items()}
        held = {tid: [dict(e) for e in entries]
                for tid, entries in _held_by_thread.items() if entries}
    threads = []
    for tid, frame in sys._current_frames().items():
        info = {
            "name": names.get(tid, f"tid-{tid}"),
            "ident": tid,
            "held": [e["name"] for e in held.get(tid, [])],
            "stack": "".join(traceback.format_stack(frame, limit=12)),
        }
        w = waiting.get(tid)
        if w is not None:
            info["waiting_for"] = w["lock"]
            info["waited_s"] = round(time.monotonic() - w["t0"], 3)
        threads.append(info)
    tel = _telemetry()
    _book(tel.counter_add, "lock.stalls", 1, lock=waiter["lock"],
          thread=names.get(ident, f"tid-{ident}"))
    # unified incident pipeline (core/incidents.py): the legacy
    # kind:"stall" record keeps its exact shape (perf_report/tests read
    # it), plus one rate-limited kind:"incident" dump with the
    # flight-recorder ring bundled — captured while still wedged
    from .. import incidents as _incidents

    _book(_incidents.report_incident, "stall", "lockdep.stall",
          round(waited_s, 3), context={
              "lock": waiter["lock"],
              "thread": names.get(ident, f"tid-{ident}"),
              "waited_s": round(waited_s, 3),
              "stall_s": float(_flags.flag("lock_stall_s")),
              "threads": threads,
          }, legacy_kind="stall")


class SanitizedLock:
    """Instrumented Lock/RLock: same acquire/release/context-manager
    surface, plus order-graph recording, re-entry detection, stall
    registration and held/wait accounting. Also implements the
    ``_is_owned``/``_release_save``/``_acquire_restore`` trio so it can
    back a ``threading.Condition``."""

    def __init__(self, name: str, reentrant: bool = False,
                 record: bool = True):
        self.name = name
        self._reentrant = bool(reentrant)
        self._record = bool(record)
        self._inner = threading.RLock() if reentrant else threading.Lock()
        _ensure_watchdog()

    def __repr__(self):
        return (f"<SanitizedLock {self.name!r} "
                f"{'rlock' if self._reentrant else 'lock'}>")

    # -- order graph ---------------------------------------------------------
    def _depth(self, held) -> int:
        return sum(1 for e in held if e["inst"] is self)

    def _check_order(self, held):
        """Record held->self edges; raise before blocking when the new
        edge would close a cycle (a lockdep 'circular dependency')."""
        held_names = []
        for e in held:
            if e["name"] != self.name and e["name"] not in held_names:
                held_names.append(e["name"])
        if not held_names:
            return
        with _state_lock:
            for h in held_names:
                path = _reachable(self.name, h)
                if path is not None:
                    cycle = " -> ".join(path + [self.name])
                    break
            else:
                for h in held_names:
                    _edges.setdefault(h, set()).add(self.name)
                return
        tel = _telemetry()
        _book(tel.counter_add, "lock.order_violations", 1, lock=self.name,
              thread=threading.current_thread().name)
        _book(tel.event, "lock_order", "lockdep.order_violation", None, {
            "lock": self.name, "held": held_names, "cycle": cycle,
            "thread": threading.current_thread().name})
        raise LockOrderError(
            f"lock-order inversion acquiring '{self.name}' while holding "
            f"{held_names} (thread '{threading.current_thread().name}'): "
            f"existing order {cycle} would close a cycle — potential "
            f"deadlock")

    def _push(self, held, t0: float):
        held.append({"name": self.name, "inst": self, "t0": t0})

    def _pop(self, held) -> Optional[float]:
        """Pop the most recent entry for this instance; returns its
        acquire time when this release drops the lock entirely (the
        outermost release of a reentrant hold)."""
        for i in range(len(held) - 1, -1, -1):
            if held[i]["inst"] is self:
                entry = held.pop(i)
                if self._depth(held) == 0:
                    return entry["t0"]
                return None
        return None

    # -- lock surface --------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        if not blocking:
            ok = self._inner.acquire(False)
            if ok:
                self._push(held, time.monotonic())
            return ok
        booking = _booking()
        if not self._reentrant and self._depth(held):
            if not booking:
                tel = _telemetry()
                _book(tel.counter_add, "lock.order_violations", 1,
                      lock=self.name, reentry=True)
            raise LockOrderError(
                f"re-entry: thread '{threading.current_thread().name}' "
                f"already holds non-reentrant lock '{self.name}' — "
                f"acquiring it again would self-deadlock")
        if not booking and self._depth(held) == 0:
            self._check_order(held)
        # fast path: uncontended acquire costs one trylock + a list append
        if self._inner.acquire(False):
            self._push(held, time.monotonic())
            if not booking and self._record:
                _book(_telemetry().counter_quiet, "lock.acquires")
            return True
        # contended: register with the watchdog, then block
        ident = threading.get_ident()
        t0 = time.monotonic()
        with _state_lock:
            _waiters[ident] = {"lock": self.name, "t0": t0,
                               "thread": threading.current_thread().name}
        try:
            if timeout is not None and timeout >= 0:
                ok = self._inner.acquire(True, timeout)
            else:
                ok = self._inner.acquire(True)
        finally:
            with _state_lock:
                _waiters.pop(ident, None)
        if not ok:
            return False
        now = time.monotonic()
        self._push(held, now)
        if not booking and self._record:
            tel = _telemetry()
            _book(tel.counter_quiet, "lock.acquires")
            _book(tel.counter_quiet, "lock.contentions")
            _book(tel.observe, f"lock.{self.name}.wait_ms",
                  (now - t0) * 1e3, kind="timer")
        return True

    def release(self):
        held = _held()
        t0 = self._pop(held)
        self._inner.release()
        if t0 is not None and self._record and not _booking():
            _book(_telemetry().observe, f"lock.{self.name}.held_ms",
                  (time.monotonic() - t0) * 1e3, kind="timer")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, et, ev, tb):
        self.release()
        return False

    def locked(self) -> bool:
        if hasattr(self._inner, "locked"):
            return self._inner.locked()
        return False   # RLock has no locked(); Condition never asks

    # -- Condition backing ---------------------------------------------------
    def _is_owned(self) -> bool:
        return self._depth(_held()) > 0

    def _release_save(self):
        """Drop ALL recursion levels (Condition.wait); returns opaque
        state for _acquire_restore."""
        held = _held()
        depth = self._depth(held)
        t0 = None
        for _ in range(depth):
            t = self._pop(held)
            if t is not None:
                t0 = t
        if hasattr(self._inner, "_release_save"):
            inner_state = self._inner._release_save()
        else:
            self._inner.release()
            inner_state = None
        if t0 is not None and self._record and not _booking():
            _book(_telemetry().observe, f"lock.{self.name}.held_ms",
                  (time.monotonic() - t0) * 1e3, kind="timer")
        return (inner_state, depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        held = _held()
        now = time.monotonic()
        for _ in range(max(depth, 1)):
            self._push(held, now)


# -- factories (the surface the lock-holding modules adopt) -------------------

def lock(name: str, record: bool = True):
    """A mutex named for the order graph. Returns a plain
    ``threading.Lock()`` when FLAGS_sanitize_locks is off (zero cost);
    an instrumented :class:`SanitizedLock` when on. ``record=False``
    keeps detection but skips telemetry booking — for locks inside the
    telemetry registry itself."""
    if not enabled():
        return threading.Lock()
    return SanitizedLock(name, reentrant=False, record=record)


def rlock(name: str, record: bool = True):
    if not enabled():
        return threading.RLock()
    return SanitizedLock(name, reentrant=True, record=record)


def condition(name: str, record: bool = True):
    """A ``threading.Condition`` whose underlying lock is sanitized
    (reentrant, matching Condition's default RLock)."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(
        SanitizedLock(name, reentrant=True, record=record))


def held_locks() -> List[str]:
    """Names of instrumented locks the CURRENT thread holds (tests)."""
    return [e["name"] for e in _held()]


# -- thread excepthook (satellite: no silent worker deaths) -------------------

_excepthook_installed = False


def install_thread_excepthook():
    """Chain onto ``threading.excepthook``: an uncaught exception in any
    worker thread books ``threads.uncaught_exceptions`` (thread name +
    exception type) and a ``kind:"thread_error"`` run-log record with
    the traceback, then falls through to the previous hook (which still
    prints to stderr). Idempotent; always on — a died-silently thread is
    a bug regardless of FLAGS_sanitize_locks."""
    global _excepthook_installed
    if _excepthook_installed:
        return
    _excepthook_installed = True
    prev = threading.excepthook

    def hook(args):
        if args.exc_type is not SystemExit:
            try:
                name = args.thread.name if args.thread is not None else "?"
                tb = "".join(traceback.format_exception(
                    args.exc_type, args.exc_value, args.exc_traceback))
                tel = _telemetry()
                tel.counter_add("threads.uncaught_exceptions", 1,
                                thread=name, exc=args.exc_type.__name__)
                # unified incident pipeline: legacy kind:"thread_error"
                # record (exact old shape) + one rate-limited
                # kind:"incident" dump with the flight-recorder ring
                from .. import incidents as _incidents

                _incidents.report_incident(
                    "thread_error", name, None, context={
                        "exc": args.exc_type.__name__,
                        "message": str(args.exc_value)[:500],
                        "traceback": tb[-4000:]},
                    legacy_kind="thread_error")
            except Exception:
                pass
        prev(args)

    threading.excepthook = hook
