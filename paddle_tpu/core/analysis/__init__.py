"""core.analysis — the repo's second static/dynamic analysis framework
(alongside core/verify.py, which checks *programs*; this package checks
the *runtime itself*).

Two halves, one discipline:

* :mod:`.lockdep` — runtime concurrency sanitizer: instrumented lock
  factories (``lock``/``rlock``/``condition``) behind
  ``FLAGS_sanitize_locks``, lock-order cycle + re-entry detection
  (typed :class:`LockOrderError`), a stall watchdog dumping all-thread
  stacks, contention/held-duration telemetry, and the
  ``threading.excepthook`` wiring that makes worker-thread deaths
  observable;
* :mod:`.concurrency_lint` — the static twin: an AST lint over the
  ``paddle_tpu/`` + ``tools/`` sources (lock-order inversions, blocking
  calls under held locks, unguarded shared fields, thread-lifecycle
  discipline) with ``# pt-lint: disable=<rule>(reason)`` suppressions.
  CLI: ``tools/lint_concurrency.py``.
"""

from .lockdep import (LockOrderError, condition,  # noqa: F401
                      install_thread_excepthook, lock, rlock)
