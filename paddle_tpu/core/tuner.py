"""Cost-model-guided autotuner: offline replay search + online A/B
promotion over the live config surface.

The stack measures everything — per-program flops/bytes/peak-HBM
(core/costmodel.py), live latency/rate windows (core/telemetry.py),
SLO baselines (core/incidents.py) — but every performance-critical knob
(``FLAGS_exec_steps_per_dispatch``, serving/decode bucket sets,
``decode_max_slots``, ``pallas_kv_chunk_tokens``, axis-rule tables +
ZeRO stage, batch size) was hand-picked, exactly like the reference's
hand-tuned ExecutionStrategy/BuildStrategy heuristics. This module
closes that loop with a MEASURED search:

* **Typed search space** (:class:`Knob` / :class:`SearchSpace`): each
  knob has a domain; candidates are validated against typed constraints
  before they are ever scored — bucket sets must be strictly increasing
  and cover the batch bound (core/flags.py ``parse_buckets``), batch
  scaling is gated by HBM-ledger headroom, sharding candidates need
  mesh evidence. Rejections are counted
  (``tuner.constraint_rejections``), never silently skipped.

* **Offline replay** (:class:`RunLogObservations` /
  :class:`ReplayModel` / :func:`offline_search`): a captured telemetry
  run log (``finalize_bench_result``-style rows or raw JSONL) is
  replayed through the cost model — measured step-ms / tokens-per-s
  percentiles ground the objective, roofline verdicts ride the report —
  to rank candidates WITHOUT touching hardware. The fused-dispatch
  amortization law ``ms(k) = device_ms + host_ms / k`` is fitted from
  observations at >= 2 distinct ``steps_per_dispatch`` points; a knob
  with no supporting evidence keeps its default
  (``tuner.insufficient_evidence``) — the tuner only proposes changes
  the log can defend. The winner is emitted as a **tuned profile**
  (JSON of flag overrides + axis-rule table + fingerprints) that
  ``bench.py`` / ``tools/bench_serving.py`` load via ``--profile``.

* **Online A/B trial** (:class:`OnlineTrial`): one candidate is flipped
  onto a SINGLE cluster replica through the PR 9 zero-downtime swap
  machinery (``ClusterController.retune_replica`` →
  ``swap_predictor(config=...)``) while the router steers a bounded
  traffic slice onto it (``Router.set_trial``). Promotion happens on
  windowed per-arm p99 deltas; the trial aborts and rolls back
  IMMEDIATELY — within one evaluation tick — when a PR 14 SLO rule
  trips mid-trial. Rollback restores the exact flag snapshot (zero
  residual overrides) and re-tunes the trial replica back to the
  incumbent config; the fleet's model version is never touched.

Telemetry: ``tuner.trials`` / ``tuner.promotions`` / ``tuner.rollbacks``
/ ``tuner.constraint_rejections`` / ``tuner.candidates`` /
``tuner.profiles_loaded`` / ``tuner.insufficient_evidence`` /
``tuner.slo_aborts`` / ``tuner.rollback_errors`` counters flow through
the usual plane (perf_report "Autotune" section, ``/metrics``), and
every profile emission / trial verdict lands as a ``kind:"tuner"`` run
log event.

CLI: ``tools/autotune.py`` (offline search, online trial, space dump);
chaos gate: ``tools/chaos_check.py --autotune``.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import flags as _flags
from . import telemetry
from .flags import BucketConfigError, ConfigError

PROFILE_FORMAT = "pt-tuned-profile-v1"

# HBM safety margin the headroom constraint keeps free (mirrors
# FLAGS_fraction_of_gpu_memory_to_use's default preallocation discipline)
HBM_SAFETY = 0.92


class TunerError(RuntimeError):
    """Autotuner failure (unusable run log, trial could not start)."""


class ProfileError(ConfigError):
    """A tuned-profile document that is malformed or the wrong format."""


# ---------------------------------------------------------------------------
# typed search space
# ---------------------------------------------------------------------------


class Knob:
    """One tunable dimension: a name, the config field it writes
    (``target``: 'flags' / 'batch_multiplier' / 'axis_rules' /
    'zero_stage'), and its candidate domain (default value FIRST)."""

    def __init__(self, name: str, values: Sequence[Any],
                 target: str = "flags", flag: Optional[str] = None,
                 doc: str = ""):
        if not values:
            raise ValueError(f"knob {name!r}: empty domain")
        self.name = name
        self.values = list(values)
        self.target = target
        self.flag = flag or name
        self.doc = doc

    @property
    def default(self):
        return self.values[0]

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "target": self.target,
                "flag": self.flag, "values": list(self.values),
                "doc": self.doc}


class Candidate:
    """One point in the search space: flag overrides + the non-flag
    levers (batch multiplier, axis-rule table, ZeRO stage). ``changes``
    counts knobs moved off their defaults (the least-change tie-break)."""

    def __init__(self, flags: Optional[Dict[str, Any]] = None,
                 batch_multiplier: float = 1.0,
                 axis_rules: Optional[List] = None,
                 zero_stage: Optional[int] = None,
                 changes: int = 0, label: str = "default"):
        self.flags = dict(flags or {})
        self.batch_multiplier = float(batch_multiplier)
        self.axis_rules = axis_rules
        self.zero_stage = zero_stage
        self.changes = int(changes)
        self.label = label

    def config_doc(self) -> Dict[str, Any]:
        """The canonical config payload (profile body + hash input)."""
        return {"flags": {k: self.flags[k] for k in sorted(self.flags)},
                "batch_multiplier": self.batch_multiplier,
                "axis_rules": self.axis_rules,
                "zero_stage": self.zero_stage}

    def fingerprint(self) -> str:
        payload = json.dumps(self.config_doc(), sort_keys=True,
                             separators=(",", ":"), default=str)
        return hashlib.sha1(payload.encode()).hexdigest()[:12]

    def __repr__(self):
        return f"Candidate({self.label}, {self.config_doc()})"


def default_space() -> List[Knob]:
    """The built-in knob set over the live flag surface. Domains derive
    from the CURRENT flag values so the incumbent config is always the
    first (default) point of every knob."""
    k0 = max(1, int(_flags.flag("exec_steps_per_dispatch")))
    max_batch = max(1, int(_flags.flag("serving_max_batch_size")))
    slots = max(1, int(_flags.flag("decode_max_slots")))
    chunk = max(1, int(_flags.flag("pallas_kv_chunk_tokens")))

    def uniq(vals):
        seen, out = set(), []
        for v in vals:
            key = json.dumps(v, sort_keys=True, default=str)
            if key not in seen:
                seen.add(key)
                out.append(v)
        return out

    serving_sets = uniq([
        str(_flags.flag("serving_buckets")),     # incumbent (often "")
        "",                                       # pow2 default
        str(max_batch),                           # one fixed bucket
        ",".join(str(b) for b in sorted({max(1, max_batch // 2),
                                         max_batch})),
    ])
    decode_sets = uniq([
        str(_flags.flag("decode_buckets")),       # incumbent
        "",                                       # one bucket of max_slots
        ",".join(str(b) for b in sorted({max(1, slots // 2), slots})),
    ])
    return [
        Knob("exec_steps_per_dispatch",
             uniq([k0] + [k for k in (1, 2, 4, 8) if k != k0]),
             doc="K-step fused dispatch (host-overhead amortization)"),
        Knob("batch_multiplier", [1.0, 2.0], target="batch_multiplier",
             doc="scale the workload batch (gated by HBM-ledger "
                 "headroom)"),
        Knob("serving_buckets", serving_sets,
             doc="micro-batch padding boundaries (jit-cache geometry)"),
        Knob("decode_max_slots",
             uniq([slots] + [s for s in (slots * 2,) if s != slots]),
             doc="concurrent decode slots (continuous-batching width)"),
        Knob("decode_buckets", decode_sets,
             doc="decode slot-array jit shapes"),
        Knob("pallas_kv_chunk_tokens",
             uniq([chunk] + [c for c in (256, 512, 1024, 2048)
                             if c != chunk]),
             doc="KV tokens per VMEM chunk of the Pallas paged-attention "
                 "kernel"),
        Knob("axis_rules", [None, "mp_first"], target="axis_rules",
             doc="logical-axis-rule table variant (needs mesh evidence)"),
        Knob("zero_stage", [0, 1, 2], target="zero_stage",
             doc="ZeRO sharded-optimizer stage (needs mesh evidence)"),
    ]


# the named axis-rule table variants the search can propose (the default
# table lives in parallel/axis_rules.py; "mp_first" prefers tensor
# parallelism for embed/mlp before falling back)
AXIS_RULE_VARIANTS: Dict[str, List[Tuple[str, Optional[str]]]] = {
    "mp_first": [("batch", "dp"), ("sequence", "sp"), ("vocab", "mp"),
                 ("heads", "mp"), ("mlp", "mp"), ("embed", "mp"),
                 ("kv", None), ("expert", "ep")],
}


class SearchSpace:
    """Knob list + candidate enumeration + typed constraint gate."""

    def __init__(self, knobs: Optional[List[Knob]] = None):
        self.knobs = list(knobs) if knobs is not None else default_space()

    def knob(self, name: str) -> Knob:
        for k in self.knobs:
            if k.name == name:
                return k
        raise KeyError(f"no knob {name!r} in the search space")

    def default_candidate(self) -> Candidate:
        return Candidate(label="default")

    def _with(self, knob: Knob, value) -> Candidate:
        cand = Candidate(changes=1, label=f"{knob.name}={value!r}")
        cand.knob = knob.name
        if knob.target == "flags":
            cand.flags[knob.flag] = value
        elif knob.target == "batch_multiplier":
            cand.batch_multiplier = float(value)
        elif knob.target == "axis_rules":
            cand.axis_rules = (AXIS_RULE_VARIANTS.get(value)
                               if isinstance(value, str) else value)
            if value is not None and cand.axis_rules is None:
                raise KeyError(f"unknown axis-rule variant {value!r}")
        elif knob.target == "zero_stage":
            cand.zero_stage = int(value)
        else:
            raise ValueError(f"knob {knob.name!r}: unknown target "
                             f"{knob.target!r}")
        return cand

    def enumerate(self) -> List[Candidate]:
        """Coordinate sweep: the default point plus one candidate per
        non-default knob value — a bounded, predictable enumeration
        (len = 1 + sum(len(domain) - 1)). Combination candidates are the
        search loop's job (offline_search combines per-knob winners)."""
        out = [self.default_candidate()]
        for knob in self.knobs:
            for value in knob.values[1:]:
                out.append(self._with(knob, value))
        telemetry.counter_add("tuner.candidates", len(out))
        return out

    # -- constraints ---------------------------------------------------------
    def check(self, cand: Candidate,
              obs: Optional["RunLogObservations"] = None) -> Optional[str]:
        """Typed constraint gate; returns the rejection reason (counted
        in ``tuner.constraint_rejections``) or None when the candidate
        is admissible."""
        reason = self._check(cand, obs)
        if reason is not None:
            telemetry.counter_add("tuner.constraint_rejections", 1,
                                  reason=reason, candidate=cand.label)
        return reason

    def _check(self, cand: Candidate,
               obs: Optional["RunLogObservations"]) -> Optional[str]:
        f = cand.flags
        k = f.get("exec_steps_per_dispatch")
        if k is not None and int(k) < 1:
            return "steps_per_dispatch_invalid"
        max_batch = int(f.get("serving_max_batch_size",
                              _flags.flag("serving_max_batch_size")))
        if "serving_buckets" in f:
            try:
                # serving bucket sets must be strictly increasing AND
                # cover max_batch_size (a set that stops short forces
                # oversized own-bucket compiles the tuner cannot cost)
                _flags.parse_buckets(f["serving_buckets"],
                                     "serving_buckets", cover=max_batch)
            except BucketConfigError:
                return "bucket_set_invalid"
        slots = int(f.get("decode_max_slots",
                          _flags.flag("decode_max_slots")))
        if slots < 1:
            return "decode_slots_invalid"
        if "decode_buckets" in f:
            try:
                _flags.parse_buckets(f["decode_buckets"], "decode_buckets",
                                     cover=slots, cover_exact=True)
            except BucketConfigError:
                return "bucket_set_invalid"
        chunk = f.get("pallas_kv_chunk_tokens")
        if chunk is not None and int(chunk) < 1:
            return "kv_chunk_invalid"
        if cand.batch_multiplier != 1.0:
            if cand.batch_multiplier <= 0:
                return "batch_multiplier_invalid"
            reason = self._check_hbm(cand, obs)
            if reason is not None:
                return reason
        if cand.axis_rules is not None or (cand.zero_stage or 0) > 0:
            # sharding candidates are only claimable with mesh evidence
            # in the replayed log (a 1-chip log cannot rank rule tables)
            if obs is None or obs.mesh_degree() <= 1:
                return "no_mesh_evidence"
        if cand.zero_stage is not None and \
                cand.zero_stage not in (0, 1, 2):
            return "zero_stage_invalid"
        return None

    @staticmethod
    def _check_hbm(cand: Candidate,
                   obs: Optional["RunLogObservations"]) -> Optional[str]:
        """HBM-ledger headroom gate: project the ledger at the scaled
        batch (params/optimizer state fixed, activation/temp bytes scale
        linearly) against the device capacity. No capacity or no ledger
        evidence ⇒ the scaled batch is unprovable ⇒ rejected."""
        capacity = float(_flags.flag("tuner_hbm_capacity_bytes"))
        if capacity <= 0:
            return "hbm_capacity_unknown"
        if obs is None:
            return "hbm_no_ledger_evidence"
        fixed, scaled = obs.ledger_split()
        if fixed is None:
            return "hbm_no_ledger_evidence"
        projected = fixed + scaled * cand.batch_multiplier
        if projected > capacity * HBM_SAFETY:
            return "hbm_headroom"
        return None


# ---------------------------------------------------------------------------
# offline replay: observations + cost model
# ---------------------------------------------------------------------------


def _pct(vals: Sequence[float], q: float) -> float:
    s = sorted(vals)
    if not s:
        return float("nan")
    idx = min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))
    return s[idx]


class RunLogObservations:
    """Everything the replay needs, extracted from captured telemetry:
    step-time observations keyed by (steps_per_dispatch, batch), decode
    tokens/s observations, per-program roofline records, last gauges and
    summed counters. Accepts raw telemetry JSONL records AND
    finalize_bench_result-style bench rows (one file may mix both)."""

    def __init__(self):
        self.step_rows: List[Dict[str, Any]] = []
        self.tokens_rows: List[Dict[str, Any]] = []
        self.cost_programs: List[Dict[str, Any]] = []
        self.gauges: Dict[str, Any] = {}
        self.counters: Dict[str, float] = {}
        self.mesh_shape: Optional[Dict[str, int]] = None
        self.run_ms: List[float] = []
        self.run_steps_ms: List[float] = []
        self.sources: List[str] = []
        self.malformed = 0

    # -- loading -------------------------------------------------------------
    @classmethod
    def load(cls, paths) -> "RunLogObservations":
        obs = cls()
        for path in ([paths] if isinstance(paths, str) else list(paths)):
            obs.sources.append(os.path.abspath(path))
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        obs.malformed += 1
                        continue
                    obs.add(doc)
        obs.finish()
        return obs

    def add(self, doc: Any):
        if not isinstance(doc, dict):
            self.malformed += 1
            return
        if isinstance(doc.get("parsed"), dict):     # BENCH_r*.json wrapper
            doc = doc["parsed"]
        if "kind" in doc:
            self._add_record(doc)
        elif "metric" in doc and isinstance(doc.get("value"), (int, float)):
            self._add_bench_row(doc)
        else:
            self.malformed += 1

    def _add_record(self, rec: Dict[str, Any]):
        kind, name = rec.get("kind"), rec.get("name", "")
        value = rec.get("value")
        attrs = rec.get("attrs") or {}
        if kind == "metric":
            row = {"metric": name, "value": value,
                   "unit": attrs.get("unit"), "extra": attrs}
            self._add_bench_row(row)
        elif kind == "cost" and isinstance(attrs, dict):
            self.cost_programs.append(attrs)
        elif kind == "gauge":
            self.gauges[name] = value
        elif kind == "counter" and isinstance(value, (int, float)):
            self.counters[name] = self.counters.get(name, 0.0) + value
        elif kind == "timer" and isinstance(value, (int, float)):
            if name == "executor.run_ms":
                self.run_ms.append(float(value))
            elif name == "executor.run_steps_ms":
                self.run_steps_ms.append(float(value))

    def _add_bench_row(self, row: Dict[str, Any]):
        ex = row.get("extra") or {}
        unit = str(row.get("unit") or "").lower()
        ms = ex.get("ms_per_step")
        if isinstance(ms, (int, float)):
            self.step_rows.append({
                "k": max(1, int(ex.get("steps_per_dispatch") or 1)),
                "batch": ex.get("batch"),
                "ms_per_step": float(ms),
                "metric": row.get("metric")})
        if "tokens/s" in unit or "tok/s" in unit:
            self.tokens_rows.append({
                "tokens_per_s": float(row["value"]),
                "config": dict(ex)})
        if isinstance(ex.get("mesh_shape"), dict):
            self.mesh_shape = {str(a): int(s)
                               for a, s in ex["mesh_shape"].items()}

    def finish(self):
        """Derive step observations from raw timer samples when the log
        carries no bench rows: executor.run_ms is per-step at k=1;
        executor.run_steps_ms is per-DISPATCH, divided by the fused k
        recovered from the fused_steps/fused_dispatches counters."""
        if self.run_ms and not any(r["k"] == 1 for r in self.step_rows):
            self.step_rows.append({
                "k": 1, "batch": None,
                "ms_per_step": _pct(self.run_ms, 0.5),
                "metric": "executor.run_ms"})
        disp = self.counters.get("executor.fused_dispatches", 0)
        steps = self.counters.get("executor.fused_steps", 0)
        if self.run_steps_ms and disp > 0 and steps > 0:
            k = max(1, int(round(steps / disp)))
            if not any(r["k"] == k for r in self.step_rows):
                self.step_rows.append({
                    "k": k, "batch": None,
                    "ms_per_step": _pct(self.run_steps_ms, 0.5) / k,
                    "metric": "executor.run_steps_ms"})
        telemetry.counter_add(
            "tuner.replay_observations",
            len(self.step_rows) + len(self.tokens_rows)
            + len(self.cost_programs))

    # -- derived evidence ----------------------------------------------------
    def mesh_degree(self) -> int:
        if not self.mesh_shape:
            return 1
        deg = 1
        for s in self.mesh_shape.values():
            deg *= max(1, int(s))
        return deg

    def ledger_split(self) -> Tuple[Optional[float], float]:
        """(fixed_bytes, batch_scaled_bytes) from the captured gauges:
        params + optimizer state are batch-invariant, activation/temp
        bytes scale with batch. (None, 0) without ledger evidence."""
        total = self.gauges.get("mem.hbm_total_bytes")
        if not isinstance(total, (int, float)):
            return None, 0.0
        fixed = 0.0
        for g in ("mem.param_bytes", "mem.opt_state_bytes"):
            v = self.gauges.get(g)
            if isinstance(v, (int, float)):
                fixed += float(v)
        return fixed, max(0.0, float(total) - fixed)

    def roofline_summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self.cost_programs:
            v = str(rec.get("roofline", "unknown"))
            out[v] = out.get(v, 0) + 1
        return out

    def base_batch(self) -> Optional[int]:
        batches = [r["batch"] for r in self.step_rows
                   if isinstance(r.get("batch"), (int, float))]
        return int(batches[-1]) if batches else None


class ReplayModel:
    """The measured objective, in order of trust:

    1. **measured** — per-k median ms_per_step straight from the log: a
       candidate whose dispatch depth WAS captured scores its measured
       value (this is what catches a hand-picked k that is wrong for
       the actual hardware — e.g. a lax.scan that LOSES on CPU);
    2. **modeled** — the fused-dispatch amortization law
       ``ms_per_step(k) = device_ms + host_ms / k`` least-squares
       fitted on x = 1/k from >= 2 distinct observed k, used ONLY when
       the fit is physically valid (host_ms >= 0, device_ms > 0): it
       extrapolates to unobserved k and scales device time linearly
       with batch (the objective is ms per base-batch-equivalent step,
       so batch scaling amortizes the host term);
    3. **none** — anything else returns None
       (``tuner.insufficient_evidence``): the tuner never invents a win
       the log cannot defend."""

    def __init__(self, obs: RunLogObservations):
        self.obs = obs
        self.measured: Dict[int, float] = {}
        self.device_ms: Optional[float] = None
        self.host_ms: Optional[float] = None
        self.base_k = 1
        self._fit()

    def _fit(self):
        by_k: Dict[int, List[float]] = {}
        for r in self.obs.step_rows:
            by_k.setdefault(int(r["k"]), []).append(float(r["ms_per_step"]))
        if not by_k:
            return
        self.measured = {k: _pct(v, 0.5) for k, v in sorted(by_k.items())}
        self.base_k = min(self.measured)
        pts = sorted(self.measured.items())
        if len(pts) < 2:
            return
        # least squares ms = device + host * (1/k)
        xs = [1.0 / k for k, _ in pts]
        ys = [ms for _, ms in pts]
        n = len(pts)
        mx, my = sum(xs) / n, sum(ys) / n
        denom = sum((x - mx) ** 2 for x in xs)
        host = (sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
                if denom > 0 else 0.0)
        device = my - host * mx
        if host >= 0.0 and device > 0.0:
            self.host_ms = host
            self.device_ms = device
        # else: the log contradicts the amortization law (e.g. the
        # fused scan LOSES on this backend) — no extrapolation, the
        # measured per-k table is the only evidence

    def has_evidence(self) -> bool:
        return bool(self.measured)

    def fit_valid(self) -> bool:
        return self.device_ms is not None and self.host_ms is not None

    def predict_step_ms(self, k: int, batch_multiplier: float = 1.0
                        ) -> Optional[Tuple[float, str]]:
        """(predicted ms per base-batch-equivalent step, basis) at
        dispatch depth k and scaled batch; None when the evidence
        cannot support the point."""
        k = max(1, int(k))
        if batch_multiplier == 1.0 and k in self.measured:
            return self.measured[k], "measured"
        if self.fit_valid():
            assert self.device_ms is not None and self.host_ms is not None
            ms = (self.device_ms * batch_multiplier + self.host_ms / k)
            return ms / batch_multiplier, "modeled"
        return None

    def default_objective(self) -> Optional[float]:
        got = self.predict_step_ms(
            max(1, int(_flags.flag("exec_steps_per_dispatch"))))
        if got is not None:
            return got[0]
        # the incumbent k was never captured and no fit extrapolates to
        # it: fall back to the base measured point so candidates still
        # have a reference (conservative — the incumbent is assumed no
        # worse than the best captured run)
        return self.measured.get(self.base_k)

    def score(self, cand: Candidate) -> Tuple[Optional[float], str]:
        """(replayed objective, basis) for one candidate. Knobs the
        model has no evidence for leave the objective at the default's
        (basis 'default'): the candidate cannot claim a win."""
        k = int(cand.flags.get("exec_steps_per_dispatch",
                               _flags.flag("exec_steps_per_dispatch")))
        touches_model = ("exec_steps_per_dispatch" in cand.flags
                         or cand.batch_multiplier != 1.0)
        if not touches_model:
            return self.default_objective(), "default"
        got = self.predict_step_ms(k, cand.batch_multiplier)
        if got is None:
            telemetry.counter_add("tuner.insufficient_evidence", 1,
                                  candidate=cand.label)
            return self.default_objective(), "default"
        return got


class SearchResult:
    def __init__(self, ranked, best, default_score, objective, obs):
        self.ranked: List[Dict[str, Any]] = ranked
        self.best: Optional[Candidate] = best
        self.default_score = default_score
        self.objective = objective
        self.obs = obs

    def improved(self) -> bool:
        if self.best is None or self.default_score is None:
            return False
        top = self.ranked[0]
        return top["score"] is not None and \
            top["score"] < self.default_score


def offline_search(obs: RunLogObservations,
                   space: Optional[SearchSpace] = None) -> SearchResult:
    """Rank the admissible candidates by replayed objective (ms per
    base-batch-equivalent step, lower is better), then try ONE combined
    candidate merging every per-knob winner — greedy coordinate search
    with a single combination pass, bounded and deterministic."""
    space = space or SearchSpace()
    model = ReplayModel(obs)
    if not model.has_evidence():
        raise TunerError(
            "run log carries no step-time observations (no bench rows, "
            "no executor.run_ms samples) — nothing to replay")
    default_score = model.default_objective()
    scored: List[Dict[str, Any]] = []
    # the best improving candidate PER KNOB (each sweep candidate moves
    # exactly one knob) — the combination pass merges across knobs only
    winners: Dict[str, Tuple[float, Candidate]] = {}
    for cand in space.enumerate():
        reason = space.check(cand, obs)
        if reason is not None:
            scored.append({"candidate": cand, "score": None,
                           "basis": "rejected", "reason": reason})
            continue
        score, basis = model.score(cand)
        scored.append({"candidate": cand, "score": score, "basis": basis})
        if score is not None and default_score is not None and \
                basis in ("modeled", "measured") and \
                score < default_score:
            knob = getattr(cand, "knob", cand.label)
            if knob not in winners or score < winners[knob][0]:
                winners[knob] = (score, cand)
    if len(winners) > 1:
        merged = Candidate(changes=len(winners), label="combined")
        for _score, w in winners.values():
            merged.flags.update(w.flags)
            if w.batch_multiplier != 1.0:
                merged.batch_multiplier = w.batch_multiplier
        if space.check(merged, obs) is None:
            score, basis = model.score(merged)
            scored.append({"candidate": merged, "score": score,
                           "basis": basis})
    admissible = [s for s in scored if s["score"] is not None]
    # rank: best objective first; ties prefer the fewest changes (the
    # incumbent wins a dead heat)
    admissible.sort(key=lambda s: (s["score"], s["candidate"].changes))
    rejected = [s for s in scored if s["score"] is None]
    ranked = admissible + rejected
    best = admissible[0]["candidate"] if admissible else None
    return SearchResult(ranked, best, default_score,
                        "step_ms_per_base_batch", obs)


# ---------------------------------------------------------------------------
# tuned profiles
# ---------------------------------------------------------------------------

_active_profile: List[Optional[Dict[str, Any]]] = [None]


def make_profile(cand: Candidate, *, objective: str,
                 replayed: Optional[float],
                 default_objective: Optional[float],
                 origin: Optional[Dict[str, Any]] = None,
                 workload: str = "") -> Dict[str, Any]:
    """Build the tuned-profile document the bench harness loads via
    ``--profile``. The profile hash covers the CONFIG payload only, so
    re-deriving the same config from a different log hashes identically."""
    from ..parallel import axis_rules as _axis
    try:
        from ..ops import pallas as _pallas
        pallas_fp = _pallas.kernels_fingerprint()
    except Exception:
        pallas_fp = None
    doc = {
        "format": PROFILE_FORMAT,
        "profile_hash": cand.fingerprint(),
        "workload": workload,
        "origin": dict(origin or {}),
        "flags": {k: cand.flags[k] for k in sorted(cand.flags)},
        "batch_multiplier": cand.batch_multiplier,
        "axis_rules": cand.axis_rules,
        "zero_stage": cand.zero_stage,
        "objective": {"name": objective, "replayed": replayed,
                      "default": default_objective},
        "fingerprints": {"axis_rules": _axis.fingerprint(),
                         "pallas_kernels": pallas_fp},
    }
    return doc


def save_profile(doc: Dict[str, Any], path: str):
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")


def load_profile(path: str) -> Dict[str, Any]:
    """Load + validate a tuned profile (typed ProfileError on junk)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        raise ProfileError(f"cannot read profile {path!r}: {e}") from e
    if not isinstance(doc, dict) or doc.get("format") != PROFILE_FORMAT:
        raise ProfileError(
            f"{path!r} is not a {PROFILE_FORMAT} document "
            f"(format={doc.get('format') if isinstance(doc, dict) else None!r})")
    if not isinstance(doc.get("flags"), dict):
        raise ProfileError(f"profile {path!r}: 'flags' must be an object")
    if not isinstance(doc.get("profile_hash"), str):
        raise ProfileError(f"profile {path!r}: missing profile_hash")
    return doc


def apply_profile(doc: Dict[str, Any],
                  origin_path: str = "") -> Dict[str, Any]:
    """Apply a tuned profile to the live config surface: validated flag
    overrides (core/flags.py apply), the axis-rule table when the
    profile carries one, and PT_BENCH_BATCH for a batch multiplier.
    Returns the prior flag values; registers the profile as ACTIVE so
    ``finalize_bench_result`` embeds its provenance in every BENCH row."""
    prior = _flags.apply(doc.get("flags") or {})
    if doc.get("axis_rules") is not None:
        from ..parallel import axis_rules as _axis

        _axis.set_rules([tuple(r) for r in doc["axis_rules"]])
    mult = float(doc.get("batch_multiplier") or 1.0)
    if mult != 1.0 and os.environ.get("PT_BENCH_BATCH"):
        os.environ["PT_BENCH_BATCH"] = str(
            max(1, int(round(int(os.environ["PT_BENCH_BATCH"]) * mult))))
    _active_profile[0] = dict(doc)
    if origin_path:
        _active_profile[0].setdefault("origin", {})
        _active_profile[0]["origin"].setdefault("path", origin_path)
    telemetry.counter_add("tuner.profiles_loaded", 1,
                          profile=doc.get("profile_hash"))
    telemetry.event("tuner", "profile_applied", None,
                    {"profile_hash": doc.get("profile_hash"),
                     "workload": doc.get("workload"),
                     "flags": doc.get("flags")})
    return prior


def active_profile() -> Optional[Dict[str, Any]]:
    return _active_profile[0]


def clear_active_profile():
    _active_profile[0] = None


def profile_provenance():
    """What finalize_bench_result embeds as ``extra.tuned_profile``: the
    active profile's {profile_hash, origin} — or the literal
    "hand-picked" so BENCH history always distinguishes tuned rows."""
    prof = _active_profile[0]
    if prof is None:
        return "hand-picked"
    origin = prof.get("origin") or {}
    return {"profile_hash": prof.get("profile_hash"),
            "origin": origin.get("run_id") or origin.get("run_log")
            or origin.get("path") or "unknown"}


# ---------------------------------------------------------------------------
# online A/B trial
# ---------------------------------------------------------------------------


class TrialResult:
    def __init__(self, status: str, reason: str, evals: int,
                 trial_p99: Optional[float] = None,
                 control_p99: Optional[float] = None):
        self.status = status          # "promoted" | "rolled_back"
        self.reason = reason
        self.evals = evals
        self.trial_p99 = trial_p99
        self.control_p99 = control_p99

    def as_dict(self) -> Dict[str, Any]:
        return {"status": self.status, "reason": self.reason,
                "evals": self.evals, "trial_p99": self.trial_p99,
                "control_p99": self.control_p99}

    def __repr__(self):
        return f"TrialResult({self.as_dict()})"


class OnlineTrial:
    """A/B-flip one candidate's FLAG overrides onto a single cluster
    replica (PR 9 swap machinery), steer a bounded traffic slice there,
    and promote or roll back on measured per-arm p99 deltas.

    Safety contract:

    * the incumbent flag surface is snapshotted before application and
      restored EXACTLY on rollback — zero residual overrides;
    * the fleet's model version is never changed by the trial; rollback
      leaves every replica on the incumbent version and config;
    * an SLO rule trip (core/incidents.py) mid-trial aborts within ONE
      evaluation tick (``tuner.slo_aborts``), and every rollback books
      exactly one ``tuner.rollbacks`` increment.
    """

    def __init__(self, cluster, candidate_flags: Dict[str, Any],
                 fraction: Optional[float] = None,
                 eval_interval_s: Optional[float] = None,
                 min_requests: Optional[int] = None,
                 promote_ratio: Optional[float] = None,
                 abort_ratio: Optional[float] = None,
                 max_evals: Optional[int] = None,
                 label: str = "candidate"):
        self.cluster = cluster
        self.router = cluster.router
        self.candidate_flags = dict(candidate_flags)
        self.fraction = float(_flags.flag("tuner_traffic_fraction")
                              if fraction is None else fraction)
        self.eval_interval_s = float(_flags.flag("tuner_eval_interval_s")
                                     if eval_interval_s is None
                                     else eval_interval_s)
        self.min_requests = int(_flags.flag("tuner_min_requests")
                                if min_requests is None else min_requests)
        self.promote_ratio = float(_flags.flag("tuner_promote_ratio")
                                   if promote_ratio is None
                                   else promote_ratio)
        self.abort_ratio = float(_flags.flag("tuner_abort_ratio")
                                 if abort_ratio is None else abort_ratio)
        self.max_evals = int(_flags.flag("tuner_max_evals")
                             if max_evals is None else max_evals)
        self.label = label
        self.trial_replica: Optional[str] = None
        self.result: Optional[TrialResult] = None
        self._snapshot: Optional[Dict[str, Any]] = None
        self._incumbent_version: Optional[int] = None
        self._slo_base = 0
        self._t0 = 0.0
        self._evals = 0
        self._started = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "OnlineTrial":
        """Snapshot the incumbent, apply the candidate flags, flip ONE
        replica onto the candidate config through the swap machinery and
        open the traffic split. On any application failure the trial
        rolls back immediately and raises TunerError."""
        if self._started:
            raise TunerError("trial already started")
        handle = next((h for h in self.router.handles() if h.ready), None)
        if handle is None:
            raise TunerError("no ready replica to run the trial on")
        telemetry.counter_add("tuner.trials", 1, candidate=self.label)
        telemetry.event("tuner", "trial_started", None,
                        {"candidate": self.label,
                         "flags": self.candidate_flags,
                         "replica": handle.name,
                         "fraction": self.fraction})
        self._started = True
        self.trial_replica = handle.name
        self._snapshot = _flags.snapshot()
        self._incumbent_version = self.cluster.current_version
        self._slo_base = int(telemetry.counters().get("slo.trips", 0))
        self._t0 = time.time()
        try:
            _flags.apply(self.candidate_flags)
        except ConfigError:
            self._rollback("candidate_invalid", retune=False)
            raise
        self.router.set_trial(handle.name, self.fraction)
        if not self.cluster.retune_replica(handle.name):
            self._rollback("apply_failed")
            raise TunerError(
                f"candidate config never took on {handle.name} "
                f"(swap failed) — rolled back")
        # arm latency evidence starts AFTER the candidate is live
        self._t0 = time.time()
        return self

    def _arm_latencies(self) -> Tuple[List[float], List[float]]:
        trial, control = [], []
        for h in self.router.handles():
            lats = h.dispatch_latencies(self._t0)
            if h.name == self.trial_replica:
                trial = lats
            else:
                control.extend(lats)
        return trial, control

    def _slo_tripped(self) -> bool:
        if int(telemetry.counters().get("slo.trips", 0)) > self._slo_base:
            return True
        try:
            from . import incidents

            if incidents.armed():
                wd = incidents.watchdog()
                return bool(wd.health()["firing"])
        except Exception:
            pass
        return False

    def evaluate_once(self, now: Optional[float] = None
                      ) -> Optional[TrialResult]:
        """One evaluation tick: SLO check first (a trip aborts HERE,
        before any latency arithmetic), then the per-arm p99 verdict.
        Returns the final TrialResult or None while undecided."""
        if self.result is not None:
            return self.result
        if not self._started:
            raise TunerError("trial not started")
        self._evals += 1
        from . import incidents

        incidents.tick(now)
        if self._slo_tripped():
            telemetry.counter_add("tuner.slo_aborts", 1,
                                  candidate=self.label)
            return self._rollback("slo_trip")
        trial, control = self._arm_latencies()
        tp99 = _pct(trial, 0.99) if trial else None
        cp99 = _pct(control, 0.99) if control else None
        if len(trial) >= self.min_requests and \
                len(control) >= self.min_requests:
            assert tp99 is not None and cp99 is not None
            if tp99 >= cp99 * self.abort_ratio:
                return self._rollback("latency_regression",
                                      tp99=tp99, cp99=cp99)
            if tp99 <= cp99 * self.promote_ratio:
                return self._promote(tp99, cp99)
        if self._evals >= self.max_evals:
            return self._rollback("undecided", tp99=tp99, cp99=cp99)
        return None

    def run(self) -> TrialResult:
        """Drive evaluation ticks at the configured cadence until the
        trial resolves (the CLI entry point; tests call evaluate_once
        directly for determinism)."""
        if not self._started:
            self.start()
        while self.result is None:
            time.sleep(self.eval_interval_s)
            self.evaluate_once()
        return self.result

    # -- verdicts ------------------------------------------------------------
    def _promote(self, tp99: float, cp99: float) -> TrialResult:
        """Promote the candidate fleet-wide: the flags stay applied and
        every OTHER replica is re-tuned onto the candidate config (the
        rolling one-at-a-time discipline of roll_to). The model version
        is untouched — this was a config trial."""
        for h in self.router.handles():
            if h.name != self.trial_replica:
                self.cluster.retune_replica(h.name)
        self.router.clear_trial()
        telemetry.counter_add("tuner.promotions", 1, candidate=self.label)
        self.result = TrialResult("promoted", "latency_win", self._evals,
                                  tp99, cp99)
        telemetry.event("tuner", "trial_promoted", tp99,
                        self.result.as_dict())
        return self.result

    def _rollback(self, reason: str, tp99=None, cp99=None,
                  retune: bool = True) -> TrialResult:
        """Restore the exact incumbent config. Exactly one
        ``tuner.rollbacks`` increment per trial, guarded by the result
        latch."""
        if self.result is not None:
            return self.result
        assert self._snapshot is not None
        _flags.apply(self._snapshot)
        self.router.clear_trial()
        if retune and self.trial_replica is not None:
            # the replica must come back to the incumbent config even
            # under injected faults: retry the re-tune a few times
            ok = False
            for _ in range(5):
                if self.cluster.retune_replica(self.trial_replica):
                    ok = True
                    break
                time.sleep(0.05)
            if not ok:
                telemetry.counter_add("tuner.rollback_errors", 1,
                                      replica=self.trial_replica)
        telemetry.counter_add("tuner.rollbacks", 1, candidate=self.label,
                              reason=reason)
        self.result = TrialResult("rolled_back", reason, self._evals,
                                  tp99, cp99)
        telemetry.event("tuner", "trial_rolled_back", tp99,
                        dict(self.result.as_dict(),
                             incumbent_version=self._incumbent_version))
        return self.result
