"""SelectedRows — the sparse-gradient value type.

Capability mirror of the reference SelectedRows
(framework/selected_rows.h:41): a (rows, values) pair representing a
tall tensor where only `rows` are populated — the gradient of an
embedding lookup touches batch-many rows of a vocab-sized table, and
materialising the dense [V, D] gradient wastes memory and an HBM pass.

Static-shape twist: on XLA `rows` has the fixed length of the lookup's
id count (duplicates allowed — consumers scatter-ADD, so duplicate rows
accumulate exactly like the reference's merge step). SelectedRows
values flow between ops inside a traced program like any other env
value; the ops that understand them are:

  lookup_table_v2 grad (is_sparse=True)  — produces them
  sum (gradient accumulation)            — concatenates them (mixed
                                           sparse+dense densifies)
  sgd                                    — true scatter-row update
  every other optimizer op               — densifies via _dense_grad
                                           (optimizer_ops.py) before
                                           updating

Ops outside that set do not understand SelectedRows; reaching one is a
programming error that surfaces as a type error at trace time.
"""

from __future__ import annotations


class SelectedRows:
    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows          # [N] int32 row ids (duplicates ok)
        self.values = values      # [N, D] row gradients
        self.height = int(height)  # dense dim 0 (vocab size)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def to_dense(self):
        import jax.numpy as jnp

        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nrows={self.values.shape[0]})")


def merge_duplicates(sr: "SelectedRows"):
    """Reference merge step (operators/math/selected_rows_functor.cc
    MergeAdd) under static shapes: sort rows, sum each duplicate group's
    values into its first slot. Returns (rows_u [N] int32, values_u
    [N, D]) where unused (duplicate) slots carry row id == height — a
    sentinel consumers scatter with mode='drop' and mask on gather.
    Needed because moment-based optimizers must see each touched row's
    TOTAL gradient once, not one partial update per occurrence."""
    import jax.numpy as jnp

    rows, values = sr.rows, sr.values
    n = rows.shape[0]
    order = jnp.argsort(rows)
    sr_rows = rows[order]
    sr_vals = values[order]
    first = jnp.concatenate([jnp.ones((1,), bool),
                             sr_rows[1:] != sr_rows[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1       # [N] group index
    values_u = jnp.zeros_like(sr_vals).at[seg].add(sr_vals)
    rows_u = jnp.full((n,), sr.height, sr_rows.dtype).at[seg].set(sr_rows)
    return rows_u, values_u


def concat(parts):
    """Gradient accumulation of SelectedRows = row concatenation
    (reference: the SelectedRows branch of sum_op.cc; duplicates merge
    at scatter time)."""
    import jax.numpy as jnp

    assert parts and all(isinstance(p, SelectedRows) for p in parts)
    h = parts[0].height
    return SelectedRows(jnp.concatenate([p.rows for p in parts]),
                        jnp.concatenate([p.values for p in parts]), h)
