"""Cost & memory observability plane — per-compile XLA cost/memory
capture, the HBM ledger, live MFU/roofline gauges, and OOM forensics.

Capability mirror of the reference's profiler + allocator accounting
(platform/profiler.h, memory/allocation stats): the repo already
measures *time* (PR 1 telemetry, PR 6 tracing); this module measures
*flops and bytes*. Three surfaces:

* **Per-compile capture.** Every executor/predictor compile runs the
  XLA AOT analyses over the jitted function, keyed by the existing
  compile-cache entry: ``Lowered.cost_analysis()`` (flops, bytes
  accessed, transcendentals — pre-optimization, nearly free because the
  trace cache is shared with the first execution) and, at capture level
  ``full``, ``Lowered.compile()`` → ``Compiled.cost_analysis()`` +
  ``memory_analysis()`` (post-optimization flops plus peak/argument/
  output/temp bytes — one extra XLA compile, so ``full`` is opt-in).
  Backends that expose neither degrade gracefully: every failed probe
  is COUNTED (``costmodel.unavailable``), never raised — CPU CI stays
  green.

* **HBM ledger + live gauges.** ``mem.param_bytes`` /
  ``mem.opt_state_bytes`` (persistable split measured at capture,
  composing with PR 7's ``sharding.optimizer_state_bytes*`` gauges when
  ZeRO shards the state), ``mem.peak_temp_bytes`` (max scratch over the
  cached programs), ``mem.hbm_total_bytes`` (the composed ledger
  verdict), per-serving-bucket footprints
  (``mem.serving.bucket<B>_peak_bytes``, captured at engine warmup and
  exposed in ``/v1/stats``), the decode engine's preallocated KV page
  pool (``mem.serving.kv_pool_bytes`` / ``kv_used_bytes`` /
  ``kv_high_water_bytes`` — serving/kv_cache.py, what lets decode
  admission refuse would-OOM requests with a typed error), plus a live
  MFU gauge
  (``cost.live_mfu`` = windowed ``cost.dispatch_flops`` rate ÷ peak
  device flops from the device table / ``FLAGS_device_peak_flops``)
  and a per-program roofline verdict (compute- vs memory-bound by
  arithmetic intensity against the device ridge point). All published
  on the live metrics plane (``/metrics`` → ``pt_cost_*``/``pt_mem_*``).

* **OOM forensics.** An allocation failure (RESOURCE_EXHAUSTED) during
  dispatch or compile dumps a ``kind:"oom"`` record into the run log —
  ledger snapshot + top-N cached programs by peak bytes + the offending
  program — and re-raises as a typed ``OutOfMemoryError`` instead of an
  opaque backend error.

Capture levels (``FLAGS_cost_capture``): ``off`` | ``cost`` (lowered
analyses only) | ``full`` (adds the AOT compile for memory stats) |
``auto`` (default — ``cost`` when the run is instrumented, i.e. a
telemetry sink or metrics server is active, else ``off``; bare test
runs pay nothing).

Render a run log's ledger + per-program cost table with
``tools/mem_report.py``; ``tools/perf_report.py`` gains a
"Memory & cost" section.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry
from .analysis import lockdep as _lockdep
from .flags import flag as _flag

# -- typed OOM error ----------------------------------------------------------


class OutOfMemoryError(RuntimeError):
    """Device allocation failure (RESOURCE_EXHAUSTED), raised after the
    OOM-forensics record landed in the run log. Deliberately NOT an
    RPC-recoverable error: ElasticRunner must not silently restart an
    OOMing step loop."""


_OOM_MARKERS = ("resource_exhausted", "out of memory", "allocation failure")


def is_oom_error(err: BaseException) -> bool:
    msg = f"{type(err).__name__}: {err}".lower()
    return any(m in msg for m in _OOM_MARKERS)


# -- device table -------------------------------------------------------------
# (peak dense flops/s, peak HBM bytes/s) by device_kind substring, first
# match wins. The flops column mirrors tools/bench_models.py's historical
# table (which now delegates here) so BENCH MFU numbers are unchanged;
# unknown kinds (incl. the CPU CI backend) fall through to the v5e row —
# override with FLAGS_device_peak_flops / FLAGS_device_peak_bw.
_DEVICE_TABLE: List[Tuple[str, float, float]] = [
    ("v5p", 459e12, 2765e9),
    ("v5 p", 459e12, 2765e9),
    ("v4", 275e12, 1228e9),
    ("v6", 918e12, 1640e9),
    ("trillium", 918e12, 1640e9),
]
_DEFAULT_PEAK = (197e12, 819e9)  # v5e / v5 lite / unknown


def _device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind.lower()
    except Exception:
        return "unknown"


def peak_device_flops() -> float:
    """Peak dense flops/s of one device — FLAGS_device_peak_flops wins
    when > 0, else the device table keyed on jax device_kind."""
    override = float(_flag("device_peak_flops"))
    if override > 0:
        return override
    kind = _device_kind()
    for sub, flops, _bw in _DEVICE_TABLE:
        if sub in kind:
            return flops
    return _DEFAULT_PEAK[0]


def peak_device_bandwidth() -> float:
    """Peak HBM bytes/s of one device (roofline denominator) —
    FLAGS_device_peak_bw wins when > 0, else the device table."""
    override = float(_flag("device_peak_bw"))
    if override > 0:
        return override
    kind = _device_kind()
    for sub, _flops, bw in _DEVICE_TABLE:
        if sub in kind:
            return bw
    return _DEFAULT_PEAK[1]


# -- cost-analysis key handling ----------------------------------------------

def normalize_cost_analysis(ca) -> Dict[str, float]:
    """One place that knows XLA's cost_analysis() shape: some backends
    return a list (one dict per partition), keys are 'flops' /
    'bytes accessed' / 'transcendentals' with per-operand variants
    ('bytes accessed0{}') we ignore. Returns a flat
    {flops, bytes_accessed, transcendentals} dict of floats (missing
    keys → 0.0). tools/audit_hlo.py renders through this too."""
    if ca is None:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        v = ca.get(key)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


# -- per-program cost records -------------------------------------------------


class ProgramCost:
    """One compiled program's captured cost/memory record."""

    __slots__ = ("key_id", "kind", "program", "steps_per_dispatch",
                 "flops", "bytes_accessed", "transcendentals",
                 "arg_bytes", "out_bytes", "temp_bytes", "peak_bytes",
                 "generated_code_bytes", "source", "devices")

    def __init__(self, key_id: str, kind: str, program: Any,
                 steps_per_dispatch: int = 1):
        self.key_id = key_id
        self.kind = kind            # "executor" | "predictor"
        self.program = program      # program uid / bucket label
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.transcendentals = 0.0
        self.arg_bytes = 0
        self.out_bytes = 0
        self.temp_bytes = 0
        self.peak_bytes = 0
        self.generated_code_bytes = 0
        self.source = "none"        # "lowered" | "compiled" | "none"
        self.devices = 1

    def flops_per_dispatch(self) -> float:
        """XLA's cost analysis counts a while/scan body ONCE regardless
        of trip count, so a K-step fused program's per-dispatch flops are
        ~body × k (measured: a k=4 scan reports ~1× the single-step
        program)."""
        return self.flops * max(1, self.steps_per_dispatch)

    def bytes_per_dispatch(self) -> float:
        return self.bytes_accessed * max(1, self.steps_per_dispatch)

    # roofline: arithmetic intensity vs the device ridge point
    def intensity(self) -> float:
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    def roofline(self) -> str:
        if not self.flops or not self.bytes_accessed:
            return "unknown"
        ridge = peak_device_flops() / max(peak_device_bandwidth(), 1.0)
        return "compute_bound" if self.intensity() >= ridge \
            else "memory_bound"

    def as_attrs(self) -> Dict[str, Any]:
        return {"key": self.key_id, "kind": self.kind,
                "program": self.program,
                "steps_per_dispatch": self.steps_per_dispatch,
                "flops": self.flops,
                "flops_per_dispatch": self.flops_per_dispatch(),
                "bytes_accessed": self.bytes_accessed,
                "transcendentals": self.transcendentals,
                "arg_bytes": self.arg_bytes, "out_bytes": self.out_bytes,
                "temp_bytes": self.temp_bytes,
                "peak_bytes": self.peak_bytes,
                "generated_code_bytes": self.generated_code_bytes,
                "source": self.source, "devices": self.devices,
                "intensity": round(self.intensity(), 4),
                "roofline": self.roofline()}


_PROGRAM_CAP = 256      # bounded registry of captured programs
_programs: "OrderedDict[str, ProgramCost]" = OrderedDict()
_lock = _lockdep.lock("costmodel.programs")
_last_mfu_set = [0.0]   # throttle for the live-MFU gauge refresh


def key_id_for(key: tuple) -> str:
    """Stable-within-the-run short id of an executor compile-cache key
    (crc32 — hash() is salted per process and would not match a reread
    run log)."""
    return f"{zlib.crc32(repr(key).encode()):08x}"


def capture_mode() -> str:
    """Resolve FLAGS_cost_capture: 'auto' means 'cost' when the run is
    instrumented (telemetry sink or metrics server active — the run
    asked for observability), else 'off' so bare CI runs pay nothing."""
    m = str(_flag("cost_capture")).strip().lower()
    if m == "auto":
        if telemetry.enabled() or telemetry.metrics_server_active():
            return "cost"
        return "off"
    return m if m in ("off", "cost", "full") else "off"


def _unavailable(stage: str, err: BaseException):
    telemetry.counter_add("costmodel.unavailable", 1, stage=stage,
                          error=f"{type(err).__name__}: {err}"[:200])


def programs() -> List[ProgramCost]:
    with _lock:
        return list(_programs.values())


def reset():
    """Clear captured program records (tests)."""
    with _lock:
        _programs.clear()
    _last_mfu_set[0] = 0.0


def _remember(rec: ProgramCost):
    with _lock:
        _programs[rec.key_id] = rec
        _programs.move_to_end(rec.key_id)
        while len(_programs) > _PROGRAM_CAP:
            _programs.popitem(last=False)
        peak = max((r.temp_bytes for r in _programs.values()), default=0)
    telemetry.counter_add("cost.captures", 1, kind=rec.kind,
                          source=rec.source)
    if peak:
        telemetry.gauge_set("mem.peak_temp_bytes", int(peak))
    telemetry.event("cost", f"costmodel.{rec.kind}", rec.flops,
                    rec.as_attrs())


def capture(lower_fn, *, key_id: str, kind: str, program: Any,
            steps_per_dispatch: int = 1) -> Optional[ProgramCost]:
    """Run the AOT analyses for one fresh compile-cache entry.

    ``lower_fn`` is a zero-arg callable returning the jax ``Lowered``
    (deferred so an un-lowerable function only costs a counted probe).
    Never raises; returns None when capture is off or nothing could be
    probed."""
    mode = capture_mode()
    if mode == "off":
        return None
    rec = ProgramCost(key_id, kind, program,
                      steps_per_dispatch=steps_per_dispatch)
    try:
        import jax

        rec.devices = max(1, jax.device_count())
    except Exception:
        pass
    try:
        lowered = lower_fn()
    except Exception as e:
        _unavailable("lower", e)
        return None
    try:
        cost = normalize_cost_analysis(lowered.cost_analysis())
        if cost:
            rec.flops = cost.get("flops", 0.0)
            rec.bytes_accessed = cost.get("bytes_accessed", 0.0)
            rec.transcendentals = cost.get("transcendentals", 0.0)
            rec.source = "lowered"
    except Exception as e:
        _unavailable("cost_analysis", e)
    if mode == "full":
        try:
            compiled = lowered.compile()
        except Exception as e:
            if is_oom_error(e):
                raise oom_forensics(program, e, where=f"{kind}.compile") \
                    from e
            _unavailable("compile", e)
            compiled = None
        if compiled is not None:
            try:
                cost = normalize_cost_analysis(compiled.cost_analysis())
                if cost:
                    rec.flops = cost.get("flops", rec.flops)
                    rec.bytes_accessed = cost.get("bytes_accessed",
                                                  rec.bytes_accessed)
                    rec.transcendentals = cost.get("transcendentals",
                                                   rec.transcendentals)
                    rec.source = "compiled"
            except Exception as e:
                _unavailable("compiled_cost_analysis", e)
            try:
                ma = compiled.memory_analysis()
                if ma is not None:
                    rec.arg_bytes = int(
                        getattr(ma, "argument_size_in_bytes", 0) or 0)
                    rec.out_bytes = int(
                        getattr(ma, "output_size_in_bytes", 0) or 0)
                    rec.temp_bytes = int(
                        getattr(ma, "temp_size_in_bytes", 0) or 0)
                    rec.generated_code_bytes = int(
                        getattr(ma, "generated_code_size_in_bytes", 0) or 0)
                    # peak working set of one execution on one device:
                    # live args + outputs + XLA scratch
                    rec.peak_bytes = (rec.arg_bytes + rec.out_bytes +
                                      rec.temp_bytes)
                    rec.source = "compiled"
            except Exception as e:
                _unavailable("memory_analysis", e)
    if rec.source == "none":
        return None
    _remember(rec)
    return rec


# -- ledger -------------------------------------------------------------------

def record_model_bytes(param_bytes: int, opt_state_bytes: int):
    """Book the persistable split measured at executor capture time into
    the ledger gauges (params vs optimizer state/counters)."""
    if param_bytes:
        telemetry.gauge_set("mem.param_bytes", int(param_bytes))
    if opt_state_bytes:
        telemetry.gauge_set("mem.opt_state_bytes", int(opt_state_bytes))
    refresh_ledger()


def split_persistable_bytes(block, names, values) -> Tuple[int, int]:
    """(param_bytes, other_state_bytes) over the named scope residents:
    is_parameter persistables are model weights, the rest (moments,
    lr counters, ...) are optimizer/run state."""
    params = other = 0
    for n, v in zip(names, values):
        if v is None:
            continue
        nbytes = int(getattr(v, "nbytes", 0) or 0)
        if not nbytes:
            try:
                a = np.asarray(v)
                nbytes = int(a.nbytes)
            except Exception:
                continue
        if block is not None and block.has_var(n):
            var = block.var(n)
            if not var.persistable:
                continue
            if getattr(var.desc, "is_parameter", False):
                params += nbytes
                continue
        other += nbytes
    return params, other


def ledger() -> Dict[str, Any]:
    """The composed HBM ledger: persistable params + optimizer state
    (per-device sharded figure from PR 7's gauges when ZeRO is active,
    else the capture-time measurement) + the worst-case compiled-program
    scratch + serving bucket footprints."""
    g = telemetry.gauges()
    param_bytes = int(g.get("mem.param_bytes", 0) or 0)
    opt_global = g.get("sharding.optimizer_state_bytes")
    opt_per_dev = g.get("sharding.optimizer_state_bytes_per_device")
    opt_bytes = int(opt_per_dev if opt_per_dev is not None
                    else g.get("mem.opt_state_bytes", 0) or 0)
    with _lock:
        recs = list(_programs.values())
    peak_temp = max((r.temp_bytes for r in recs), default=0)
    buckets = {n[len("mem.serving.bucket"):-len("_peak_bytes")]: int(v)
               for n, v in g.items()
               if n.startswith("mem.serving.bucket")
               and n.endswith("_peak_bytes")}
    # the decode engine's preallocated KV page pool (serving/kv_cache.py)
    # is RESIDENT for the process lifetime — its full preallocation, not
    # just the used pages, belongs in the composed total
    kv_pool = int(g.get("mem.serving.kv_pool_bytes", 0) or 0)
    out = {"param_bytes": param_bytes, "opt_state_bytes": opt_bytes,
           "peak_temp_bytes": int(peak_temp),
           "total_bytes": param_bytes + opt_bytes + int(peak_temp)
           + kv_pool,
           "programs": len(recs)}
    if opt_global is not None:
        out["opt_state_bytes_global"] = int(opt_global)
    if buckets:
        out["serving_bucket_bytes"] = buckets
        out["serving_peak_bytes"] = max(buckets.values())
    if kv_pool:
        out["serving_kv_pool_bytes"] = kv_pool
        out["serving_kv_used_bytes"] = int(
            g.get("mem.serving.kv_used_bytes", 0) or 0)
        out["serving_kv_high_water_bytes"] = int(
            g.get("mem.serving.kv_high_water_bytes", 0) or 0)
    # cumulative pool bytes requests did NOT privately allocate thanks
    # to a prefix-cache hit (serving/prefix_store.py) — savings, not
    # residency, so it never joins total_bytes
    kv_saved = int(g.get("mem.serving.kv_prefix_saved_bytes", 0) or 0)
    if kv_saved:
        out["serving_kv_prefix_saved_bytes"] = kv_saved
    return out


def refresh_ledger():
    """Recompute + publish the composed ledger total (called after any
    component gauge moves: executor capture, ZeRO report_state_sharding,
    serving warmup)."""
    led = ledger()
    if led["total_bytes"]:
        telemetry.gauge_set("mem.hbm_total_bytes", led["total_bytes"])


# -- dispatch accounting + live MFU ------------------------------------------

def book_dispatch(rec: Optional[ProgramCost], steps: int = 1):
    """Book one dispatch of a captured program: quiet flop/byte counters
    (per-dispatch volume is too high for per-increment JSONL) feed the
    rolling window that the live MFU gauge reads. flops_per_dispatch
    scales the body by steps_per_dispatch because XLA's cost analysis
    counts a scan/while body once regardless of trip count."""
    if rec is None or not rec.flops:
        return
    telemetry.counter_quiet("cost.dispatch_flops",
                            int(rec.flops_per_dispatch()))
    if rec.bytes_accessed:
        telemetry.counter_quiet("cost.dispatch_bytes",
                                int(rec.bytes_per_dispatch()))
    now = time.time()
    if now - _last_mfu_set[0] >= 1.0:   # 1 Hz gauge refresh, not per step
        _last_mfu_set[0] = now
        # no rounding: CPU-CI MFU values live around 1e-7 and must stay
        # nonzero in the log/gauge
        telemetry.gauge_set("cost.live_mfu", float(live_mfu()))


def live_mfu(window_s: Optional[float] = None) -> float:
    """Live model-flops utilization: windowed achieved flops/s (the
    cost.dispatch_flops rolling rate) ÷ peak device flops. The PaLM-
    style MFU discipline as a runtime gauge instead of an offline bench
    formula."""
    win = telemetry.windowed(window_s)
    wc = win["counters"].get("cost.dispatch_flops")
    if not wc:
        return 0.0
    return float(wc["rate"]) / max(peak_device_flops(), 1.0)


# -- OOM forensics ------------------------------------------------------------

def oom_forensics(program: Any, err: BaseException,
                  where: str = "dispatch", top_n: int = 8) -> OutOfMemoryError:
    """Dump the forensics record for an allocation failure and return
    the typed error to raise: ledger snapshot + the top-N cached
    programs by peak bytes + the offending program id, as one
    ``kind:"oom"`` JSONL record (and a counted ``mem.oom_events``).
    The dump rides the unified incident pipeline (core/incidents.py):
    the legacy record keeps its exact shape for mem_report, and a
    ``kind:"incident"`` record bundles it with the flight-recorder ring
    + active traces."""
    with _lock:
        recs = sorted(_programs.values(),
                      key=lambda r: -(r.peak_bytes or r.temp_bytes))[:top_n]
    top = [{"key": r.key_id, "kind": r.kind, "program": r.program,
            "peak_bytes": r.peak_bytes, "temp_bytes": r.temp_bytes,
            "arg_bytes": r.arg_bytes, "flops": r.flops} for r in recs]
    led = ledger()
    telemetry.counter_add("mem.oom_events", 1, where=where)
    from . import incidents

    incidents.report_incident(
        "oom", "costmodel.oom", None,
        context={"where": where, "program": program,
                 "error": f"{type(err).__name__}: {err}"[:500],
                 "ledger": led, "top_programs": top},
        legacy_kind="oom")
    telemetry.flush_sink()   # the process may be about to die — land it
    return OutOfMemoryError(
        f"device allocation failure in {where} of program {program!r} "
        f"(HBM ledger: {led['total_bytes']} bytes across "
        f"{led['programs']} cached programs; forensics record written "
        f"to the run log): {err}")
