"""Program IR: Program ⊃ Block ⊃ {VarDesc, OpDesc}.

Capability mirror of the reference's protobuf IR
(paddle/fluid/framework/framework.proto: OpDesc:42, VarDesc:165, BlockDesc:174,
ProgramDesc:198) and its Python builder (python/paddle/fluid/framework.py:
Variable:924, Operator:1916, Block:2507, Program:3969) — re-designed for XLA:

* Descs are plain Python dataclasses (JSON-serialisable) instead of protobuf.
* Build-time shape/dtype inference runs the op's *JAX lowering* under
  `jax.eval_shape` — one source of truth instead of separate InferShape
  functions (reference keeps per-op InferShape in C++, operator.cc:1076).
* Dynamic (batch) dims are stored as -1 and substituted with a sentinel for
  tracing; execution never depends on desc shapes.

A whole Block is later compiled into ONE jitted XLA computation by the
compiling executor (see executor.py) instead of being interpreted op-by-op
(reference hot loop: framework/executor.cc:474-481).
"""

from __future__ import annotations

import contextlib
import copy
import itertools
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from . import unique_name
from .types import VarType, convert_dtype

# Op role taxonomy (reference: framework/op_proto_maker.h OpRole)
class OpRole:
    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 0x100
    Collective = 0x200


# Sentinel used to trace dynamic dims through jax.eval_shape.
_DYN_SENTINEL = 509    # primes: two eval_shape runs at different
_DYN_SENTINEL_B = 521  # substitutions identify dynamic output dims exactly
_EVAL_SHAPE_WARNED: set = set()  # op types already warned-once about


def _json_attr(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.dtype):
        return str(v)
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


@dataclass
class VarDesc:
    """Variable metadata (reference: framework.proto VarDesc:165)."""

    name: str
    shape: Optional[tuple] = None  # None = unknown; -1 = dynamic dim
    dtype: Any = np.float32
    type: VarType = VarType.DENSE_TENSOR
    persistable: bool = False
    stop_gradient: bool = False
    lod_level: int = 0
    is_parameter: bool = False
    trainable: bool = True
    attrs: Dict[str, Any] = field(default_factory=dict)  # e.g. sharding spec

    def __post_init__(self):
        if self.shape is not None:
            self.shape = tuple(int(d) for d in self.shape)
        self.dtype = convert_dtype(self.dtype)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": str(np.dtype(self.dtype)),
            "type": self.type.value,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "lod_level": self.lod_level,
            "is_parameter": self.is_parameter,
            "trainable": self.trainable,
            "attrs": {k: _json_attr(v) for k, v in self.attrs.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "VarDesc":
        return VarDesc(
            name=d["name"],
            shape=tuple(d["shape"]) if d.get("shape") is not None else None,
            dtype=d.get("dtype", "float32"),
            type=VarType(d.get("type", "dense_tensor")),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            lod_level=d.get("lod_level", 0),
            is_parameter=d.get("is_parameter", False),
            trainable=d.get("trainable", True),
            attrs=dict(d.get("attrs", {})),
        )


class OpDesc:
    """One operator invocation (reference: framework.proto OpDesc:42).

    inputs/outputs map proto slot names to lists of variable names
    (multi-var slots exist: e.g. `sum` takes X=[a, b, c]).
    """

    __slots__ = ("type", "inputs", "outputs", "attrs", "callstack")

    def __init__(self, type: str, inputs: Dict[str, List[str]],
                 outputs: Dict[str, List[str]], attrs: Optional[Dict[str, Any]] = None):
        self.type = type
        self.inputs = {k: list(v) for k, v in inputs.items()}
        self.outputs = {k: list(v) for k, v in outputs.items()}
        self.attrs = dict(attrs or {})
        # attach Python build-site stack for error reporting
        # (reference: framework/op_call_stack.cc)
        self.callstack = traceback.format_stack(limit=6)[:-2]

    def input_names(self) -> List[str]:
        return [n for names in self.inputs.values() for n in names]

    def output_names(self) -> List[str]:
        return [n for names in self.outputs.values() for n in names]

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name: str) -> bool:
        return name in self.attrs

    def _rename_input(self, old: str, new: str):
        for slot in self.inputs:
            self.inputs[slot] = [new if n == old else n for n in self.inputs[slot]]

    def _rename_output(self, old: str, new: str):
        for slot in self.outputs:
            self.outputs[slot] = [new if n == old else n for n in self.outputs[slot]]

    @property
    def op_role(self) -> int:
        return self.attrs.get("op_role", OpRole.Forward)

    def is_backward_op(self) -> bool:
        return (self.op_role & 0xF) == OpRole.Backward

    def is_optimize_op(self) -> bool:
        return (self.op_role & 0xF) == OpRole.Optimize

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": {k: _json_attr(v) for k, v in self.attrs.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "OpDesc":
        return OpDesc(d["type"], d["inputs"], d["outputs"], d.get("attrs", {}))

    def __repr__(self):
        ins = ", ".join(f"{k}={v}" for k, v in self.inputs.items())
        outs = ", ".join(f"{k}={v}" for k, v in self.outputs.items())
        return f"Op({self.type}: {ins} -> {outs})"


class Variable:
    """Python handle to a VarDesc in a Block (reference: framework.py:924).

    Supports arithmetic operator overloads that append elementwise ops to the
    variable's block — this is what makes `a + b` inside a program build IR.
    """

    def __init__(self, block: "Block", desc: VarDesc):
        self.block = block
        self.desc = desc

    # -- metadata ------------------------------------------------------------
    @property
    def name(self) -> str:
        return self.desc.name

    @property
    def shape(self) -> Optional[tuple]:
        return self.desc.shape

    @property
    def dtype(self):
        return self.desc.dtype

    @property
    def type(self) -> VarType:
        return self.desc.type

    @property
    def persistable(self) -> bool:
        return self.desc.persistable

    @persistable.setter
    def persistable(self, v: bool):
        self.desc.persistable = v

    @property
    def stop_gradient(self) -> bool:
        return self.desc.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v: bool):
        self.desc.stop_gradient = v

    @property
    def lod_level(self) -> int:
        return self.desc.lod_level

    def astype(self, dtype) -> "Variable":
        from .. import layers

        return layers.cast(self, dtype)

    # -- operator overloads --------------------------------------------------
    def _binary(self, other, op, reverse=False):
        from .. import layers

        return layers._elementwise_binary(self, other, op, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __neg__(self):
        from .. import layers

        return layers.scale(self, scale=-1.0)

    def __matmul__(self, other):
        from .. import layers

        return layers.matmul(self, other)

    def _cmp(self, other, op):
        from .. import layers

        return layers._compare(self, other, op)

    def __lt__(self, other):
        return self._cmp(other, "less_than")

    def __le__(self, other):
        return self._cmp(other, "less_equal")

    def __gt__(self, other):
        return self._cmp(other, "greater_than")

    def __ge__(self, other):
        return self._cmp(other, "greater_equal")

    def __getitem__(self, idx):
        from .. import layers

        return layers._getitem(self, idx)

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={np.dtype(self.dtype).name}, persistable={self.persistable})")

    __str__ = __repr__


class Parameter(Variable):
    """Trainable persistable variable (reference: framework.py Parameter:5116)."""

    def __init__(self, block: "Block", desc: VarDesc, trainable: bool = True,
                 regularizer=None, optimize_attr=None):
        desc.persistable = True
        desc.is_parameter = True
        desc.trainable = trainable
        super().__init__(block, desc)
        self.regularizer = regularizer
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}

    @property
    def trainable(self) -> bool:
        return self.desc.trainable

    @trainable.setter
    def trainable(self, v: bool):
        self.desc.trainable = v


class Block:
    """Ordered list of ops + var table (reference: framework.py Block:2507)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[OpDesc] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- var management ------------------------------------------------------
    def create_var(self, name: Optional[str] = None, shape=None, dtype="float32",
                   type: VarType = VarType.DENSE_TENSOR, persistable: bool = False,
                   stop_gradient: bool = False, lod_level: int = 0, **kw) -> Variable:
        name = name or unique_name.generate("_generated_var")
        if name in self.vars:
            return self.vars[name]
        desc = VarDesc(name=name, shape=tuple(shape) if shape is not None else None,
                       dtype=dtype, type=type, persistable=persistable,
                       stop_gradient=stop_gradient, lod_level=lod_level)
        var = Variable(self, desc)
        self.vars[name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, name: str, shape, dtype="float32", trainable=True,
                         regularizer=None, optimize_attr=None) -> Parameter:
        desc = VarDesc(name=name, shape=tuple(shape), dtype=dtype, persistable=True)
        param = Parameter(self, desc, trainable=trainable, regularizer=regularizer,
                          optimize_attr=optimize_attr)
        self.vars[name] = param
        self.program._bump_version()
        return param

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError(f"Variable '{name}' not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- op management -------------------------------------------------------
    @staticmethod
    def _normalize_io(io: Optional[Dict[str, Any]]) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for slot, vals in (io or {}).items():
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            names = []
            for v in vals:
                if isinstance(v, (Variable,)):
                    names.append(v.name)
                elif isinstance(v, str):
                    names.append(v)
                else:
                    raise TypeError(f"bad io entry for slot {slot}: {type(v)}")
            out[slot] = names
        return out

    def append_op(self, type: str, inputs: Optional[Dict] = None,
                  outputs: Optional[Dict] = None, attrs: Optional[Dict] = None,
                  infer_shape: bool = True) -> OpDesc:
        op = OpDesc(type, self._normalize_io(inputs), self._normalize_io(outputs),
                    attrs)
        if "op_role" not in op.attrs:
            op.attrs["op_role"] = self.program._current_role
        dev = current_device()
        if dev is not None and "__device__" not in op.attrs:
            # pipeline-stage tag (reference: device_guard framework.py:5591)
            op.attrs["__device__"] = dev
        self.ops.append(op)
        if infer_shape:
            self._infer_op_shapes(op)
        self.program._bump_version()
        return op

    def _prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> OpDesc:
        op = OpDesc(type, self._normalize_io(inputs), self._normalize_io(outputs), attrs)
        if "op_role" not in op.attrs:
            op.attrs["op_role"] = self.program._current_role
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _infer_op_shapes(self, op: OpDesc):
        """Build-time shape/dtype inference by tracing the op's JAX lowering
        with jax.eval_shape over sentinel-substituted dynamic dims.

        Replaces the reference's per-op C++ InferShape (operator.cc:1076) with
        the lowering itself as the single source of truth.

        Dynamic dims (-1) are detected exactly by evaluating the shape
        function at TWO different sentinel substitutions: an output dim that
        changes between the runs depends on a dynamic input dim and is
        recorded as -1; a dim that agrees is genuinely static. (No value
        pattern-matching — a real dim equal to a sentinel multiple is safe.)
        """
        from . import registry
        from .flags import flag

        opdef = registry.lookup(op.type)
        if opdef is None or opdef.forward is None or opdef.skip_infer_shape:
            return
        import jax

        def debug(msg):
            if flag("infer_shape_debug"):
                import warnings

                warnings.warn(
                    f"infer_shape[{op.type}]: {msg}", stacklevel=4)

        # one var-lookup pass builds BOTH sentinel substitutions; the
        # second eval_shape only runs when a dynamic dim is present
        structs_a: Dict[str, List[Any]] = {}
        structs_b: Dict[str, List[Any]] = {}
        has_dyn = False
        for slot, names in op.inputs.items():
            lst_a, lst_b = [], []
            for n in names:
                v = self._find_var_recursive(n)
                if v is None or v.shape is None:
                    debug(f"input '{n}' has unknown shape; skipped")
                    return
                if -1 in v.shape:
                    has_dyn = True
                dt = np.dtype(v.dtype)
                lst_a.append(jax.ShapeDtypeStruct(
                    tuple(_DYN_SENTINEL if d == -1 else d
                          for d in v.shape), dt))
                lst_b.append(jax.ShapeDtypeStruct(
                    tuple(_DYN_SENTINEL_B if d == -1 else d
                          for d in v.shape), dt))
            structs_a[slot] = lst_a
            structs_b[slot] = lst_b

        def eval_at(structs):
            return jax.eval_shape(
                lambda ins: opdef.forward(ins, dict(op.attrs)), structs)

        try:
            out_a = eval_at(structs_a)
            out_b = eval_at(structs_b) if has_dyn else out_a
        except Exception as e:  # inference is best-effort; runtime uses
            debug(f"lowering raised during eval_shape: "
                  f"{type(e).__name__}: {e}")  # real arrays
            # a broken lowering degrading to shapeless vars should not be
            # fully silent: warn ONCE per op type even without the flag
            if op.type not in _EVAL_SHAPE_WARNED:
                _EVAL_SHAPE_WARNED.add(op.type)
                if not flag("infer_shape_debug"):
                    import warnings

                    warnings.warn(
                        f"infer_shape[{op.type}]: lowering raised during "
                        f"eval_shape ({type(e).__name__}); output shapes "
                        f"unknown — set FLAGS_infer_shape_debug=1 for "
                        f"per-occurrence detail", stacklevel=4)
            return

        if not isinstance(out_a, dict):
            debug(f"lowering returned {type(out_a).__name__}, expected dict")
            return
        for slot, names in op.outputs.items():
            vals_a = out_a.get(slot)
            vals_b = out_b.get(slot)
            if vals_a is None:
                continue
            if not isinstance(vals_a, (list, tuple)):
                vals_a, vals_b = [vals_a], [vals_b]
            for n, sa, sb in zip(names, vals_a, vals_b):
                v = self._find_var_recursive(n)
                if v is None or sa is None:
                    continue
                if len(sa.shape) != len(sb.shape):
                    debug(f"output '{n}' rank depends on a dynamic dim "
                          f"({sa.shape} vs {sb.shape}); skipped")
                    continue
                shape = tuple(
                    da if da == db else -1
                    for da, db in zip(sa.shape, sb.shape))
                v.desc.shape = shape
                v.desc.dtype = np.dtype(sa.dtype)

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.desc.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }

    def _load_dict(self, d: dict):
        for vd in d.get("vars", []):
            desc = VarDesc.from_dict(vd)
            if desc.is_parameter:
                self.vars[desc.name] = Parameter(self, desc, trainable=desc.trainable)
            else:
                self.vars[desc.name] = Variable(self, desc)
        for od in d.get("ops", []):
            self.ops.append(OpDesc.from_dict(od))

    def __repr__(self):
        return f"Block(idx={self.idx}, vars={len(self.vars)}, ops={len(self.ops)})"


def _collect_op_refs(ops, refs: set, seen: set):
    """Every var name the ops reference: io slots plus (conservatively)
    any string reachable through attr values — name lists carried in
    attrs (control-flow input_names/carry_names, fusion_group sub_ops
    io) keep their vars alive — recursing into attr-held sub-blocks."""

    def scan(val):
        if isinstance(val, str):
            refs.add(val)
        elif isinstance(val, Block):
            if id(val) not in seen:
                seen.add(id(val))
                _collect_op_refs(val.ops, refs, seen)
        elif isinstance(val, Program):
            if id(val) not in seen:
                seen.add(id(val))
                for blk in val.blocks:
                    _collect_op_refs(blk.ops, refs, seen)
        elif isinstance(val, dict):
            for v in val.values():
                scan(v)
        elif isinstance(val, (list, tuple)):
            for v in val:
                scan(v)

    for op in ops:
        refs.update(op.input_names())
        refs.update(op.output_names())
        for val in (op.attrs or {}).values():
            scan(val)


class Program:
    """A whole computation (reference: framework.py Program:3969).

    Holds a list of Blocks; block 0 is the global block. The compiling
    executor lowers one (program, feed-names, fetch-names) triple to a single
    jitted XLA computation, keyed on `version` for cache invalidation.
    """

    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0, -1)]
        self.current_block_idx = 0
        self.random_seed: int = 0
        self._current_role = OpRole.Forward
        self._version = 0
        # populated by append_backward: maps var name -> grad var name
        self.grad_var_map: Dict[str, str] = {}
        self._seed_counter = 0
        # process-unique, never-reused identity for executor cache keys
        # (id() can alias a GC'd program; VERDICT r1 weak #8)
        self.uid = next(Program._uid_counter)

    def _bump_version(self):
        self._version += 1

    @property
    def version(self) -> int:
        return self._version

    def next_op_seed(self) -> int:
        """Per-op RNG seed assigned at build time; runtime folds in the global
        step so random ops (dropout, …) vary per run but stay reproducible."""
        self._seed_counter += 1
        return self.random_seed * 1000003 + self._seed_counter

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        return blk

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @contextlib.contextmanager
    def _role_guard(self, role: int):
        old = self._current_role
        self._current_role = role
        try:
            yield
        finally:
            self._current_role = old

    def list_vars(self) -> Iterator[Variable]:
        for blk in self.blocks:
            yield from blk.vars.values()

    def all_parameters(self) -> List[Parameter]:
        out = []
        for blk in self.blocks:
            out.extend(blk.all_parameters())
        return out

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program. for_test=True keeps only forward ops and
        flips is_test attrs (reference: framework.py Program.clone)."""
        p = Program()
        p.random_seed = self.random_seed
        p._seed_counter = self._seed_counter
        p.blocks = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for name, var in blk.vars.items():
                desc = copy.deepcopy(var.desc)
                if isinstance(var, Parameter):
                    nb.vars[name] = Parameter(nb, desc, trainable=var.trainable)
                else:
                    nb.vars[name] = Variable(nb, desc)
            for op in blk.ops:
                if for_test and (op.is_backward_op() or op.is_optimize_op()):
                    continue
                nop = OpDesc(op.type, op.inputs, op.outputs, copy.deepcopy(op.attrs))
                if for_test and "is_test" in nop.attrs:
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        if not p.blocks:
            p.blocks = [Block(p, 0, -1)]
        if for_test:
            # dropping the backward/optimize ops orphans their VarDescs
            # (@GRAD vars, optimizer temporaries) — prune any
            # non-persistable var whose only producers were removed, so
            # the test clone verifies dead-var clean (core/verify.py)
            # and serialized eval programs don't carry training litter.
            # Source vars (feeds — no producer anywhere) always survive.
            produced: set = set()
            for blk in self.blocks:
                for op in blk.ops:
                    produced.update(op.output_names())
            refs: set = set()
            seen: set = set()
            for nb in p.blocks:
                _collect_op_refs(nb.ops, refs, seen)
            for nb in p.blocks:
                for name in [n for n, v in nb.vars.items()
                             if n in produced and n not in refs
                             and not v.desc.persistable]:
                    del nb.vars[name]
        p.grad_var_map = dict(self.grad_var_map)
        p._bump_version()
        return p

    def to_dict(self) -> dict:
        return {
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd.get("parent_idx", -1))
            blk._load_dict(bd)
            p.blocks.append(blk)
        if not p.blocks:
            p.blocks = [Block(p, 0, -1)]
        p._bump_version()
        return p

    def __repr__(self):
        nops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={nops}, version={self._version})"


# -- default program stack ---------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Scope the default programs (reference: framework.py:5455)."""
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_main, old_startup


def switch_main_program(program: Program) -> Program:
    global _main_program
    old = _main_program
    _main_program = program
    return old


# device_guard: pins subsequent ops to a pipeline stage
# (reference: framework.py:5591 device_guard — the pipeline-stage mechanism)
_current_device: Optional[str] = None


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    global _current_device
    old = _current_device
    _current_device = device
    try:
        yield
    finally:
        _current_device = old


def current_device() -> Optional[str]:
    return _current_device


_dygraph_tracer_holder = [None]


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_holder[0] is not None
