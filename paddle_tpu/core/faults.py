"""Deterministic fault injection for the distributed runtime.

The reference tests its PS failure paths with env-knob chaos (gRPC retry
envs, heart_beat_monitor timeouts) but has no seeded, auditable way to
MAKE a transport fail in a unit test. This module is that harness: a
process-global registry of named injection sites (`ps.rpc.send`,
`ps.rpc.recv`, `ps.handler`, `ps.checkpoint.save`, `serving.handler` —
the serving engine's batch loop, see paddle_tpu/serving/engine.py and
tools/chaos_check.py --serving — the generative decode engine's
`decode.step` / `decode.kv_alloc` — the continuous-batching step loop
and the KV page-pool allocator, see paddle_tpu/serving/decode.py,
serving/kv_cache.py and tools/chaos_check.py --decode — and the
crash-consistent checkpoint protocol's `ckpt.save.write` /
`ckpt.save.commit` / `ckpt.restore.read`,
see paddle_tpu/checkpoint.py and tools/chaos_check.py --checkpoint)
consulted by the transport/pserver/serving/checkpoint hot paths, driven
by a spec string so chaos runs need no code changes:

    FLAGS_fault_spec / PT_FAULT_SPEC =
        clause [ (','|';') clause ]*
    clause  = site ':' trigger [ ':' ExcName ]
    trigger = float p in (0, 1]   fire each call with probability p
            | '@' N               fire exactly on the Nth call (once)
            | '%' N               fire on every Nth call
    ExcName defaults to ConnectionError; resolved from builtins, then
    paddle_tpu.distributed.errors (RpcError, RpcDeadlineError, ...).

Examples::

    ps.rpc.send:0.1                    # drop 10% of sends
    ps.rpc.recv:@2:ConnectionError     # kill exactly the 2nd reply read
    ps.handler:%5:RuntimeError         # every 5th dispatch blows up

Determinism: every probabilistic rule owns a random.Random seeded from
(FLAGS_fault_seed, site, rule index), so the fire pattern is a pure
function of the seed and the per-site call sequence — independent sites
do not perturb each other's streams. Every injected fault bumps the
`faults.injected` telemetry counter (attrs: site, exc) and emits a
`fault` event, so a chaos run's JSONL log is a complete audit of what
was injected where (tools/chaos_check.py tallies it).

The registry re-reads the spec flag on use, so
`set_flags({'FLAGS_fault_spec': ...})` (or configure()) takes effect
mid-run; an empty spec keeps maybe_fail() at a dict-lookup of overhead.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Any, Dict, List, Optional

from . import flags as _flags
from . import telemetry
from .analysis import lockdep as _lockdep


class FaultSpecError(ValueError):
    """Malformed FLAGS_fault_spec / PT_FAULT_SPEC string."""


def _resolve_exc(name: str):
    import builtins

    exc = getattr(builtins, name, None)
    if exc is None:
        from ..distributed import errors as _derrors

        exc = getattr(_derrors, name, None)
    if not (isinstance(exc, type) and issubclass(exc, BaseException)):
        raise FaultSpecError(f"unknown exception type '{name}' in fault "
                             f"spec (builtins + distributed.errors)")
    return exc


class _Rule:
    __slots__ = ("site", "prob", "nth", "every", "exc", "rng", "spent")

    def __init__(self, site: str, trigger: str, exc_name: str,
                 seed: int, index: int):
        self.site = site
        self.prob: Optional[float] = None
        self.nth: Optional[int] = None
        self.every: Optional[int] = None
        self.exc = _resolve_exc(exc_name or "ConnectionError")
        self.spent = False
        # per-rule stream: (seed, site, index) so rules never share draws
        self.rng = random.Random(f"{seed}|{site}|{index}")
        if trigger.startswith("@"):
            self.nth = int(trigger[1:])
            if self.nth < 1:
                raise FaultSpecError(f"'@N' trigger needs N >= 1: {trigger}")
        elif trigger.startswith("%"):
            self.every = int(trigger[1:])
            if self.every < 1:
                raise FaultSpecError(f"'%N' trigger needs N >= 1: {trigger}")
        else:
            self.prob = float(trigger)
            if not 0.0 < self.prob <= 1.0:
                raise FaultSpecError(
                    f"probability trigger must be in (0, 1]: {trigger}")

    def fires(self, call_index: int) -> bool:
        """call_index is the 1-based count of calls at this rule's site."""
        if self.nth is not None:
            if self.spent or call_index != self.nth:
                return False
            self.spent = True
            return True
        if self.every is not None:
            return call_index % self.every == 0
        return self.rng.random() < self.prob


def _parse(spec: str, seed: int) -> List[_Rule]:
    rules: List[_Rule] = []
    for idx, clause in enumerate(
            c.strip() for part in spec.split(";")
            for c in part.split(",")):
        if not clause:
            continue
        bits = clause.split(":")
        if len(bits) == 2:
            site, trigger, exc = bits[0], bits[1], ""
        elif len(bits) == 3:
            site, trigger, exc = bits
        else:
            raise FaultSpecError(
                f"fault clause '{clause}' is not site:trigger[:Exc]")
        if not site:
            raise FaultSpecError(f"empty site in fault clause '{clause}'")
        rules.append(_Rule(site, trigger, exc, seed, idx))
    return rules


class FaultRegistry:
    _instance: Optional["FaultRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = _lockdep.lock("faults.registry")
        self._rules: Dict[str, List[_Rule]] = {}
        self._calls: Dict[str, int] = {}
        self._injected: Dict[str, int] = {}
        self._src: Optional[tuple] = None

    @classmethod
    def instance(cls) -> "FaultRegistry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    # -- spec tracking -------------------------------------------------------
    @staticmethod
    def _effective_spec() -> tuple:
        spec = _flags.flag("fault_spec") or \
            os.environ.get("PT_FAULT_SPEC", "")
        seed = _flags.flag("fault_seed")
        if seed == 0:
            seed = int(os.environ.get("PT_FAULT_SEED", "0") or 0)
        return spec.strip(), int(seed)

    def _sync(self):
        """(Re)parse when the flag/env spec changed — called under
        self._lock. A spec change resets call counts so nth-call rules
        are reproducible from the moment of configuration."""
        src = self._effective_spec()
        if src == self._src:
            return
        spec, seed = src
        # parse BEFORE committing _src: a malformed spec keeps raising on
        # every use (loud) instead of erroring once and going silent
        parsed = _parse(spec, seed) if spec else []
        self._src = src
        self._calls.clear()
        self._injected.clear()
        self._rules = {}
        for rule in parsed:
            self._rules.setdefault(rule.site, []).append(rule)

    # -- the injection point -------------------------------------------------
    def maybe_fail(self, site: str, **attrs: Any):
        """Raise the configured fault for `site`, if any rule fires.
        Every call counts against the site's 1-based call index whether
        or not a rule exists (so '@N' specs configured mid-run still
        reference the site's true call history from config time)."""
        with self._lock:
            self._sync()
            if not self._rules:
                return
            rules = self._rules.get(site)
            if not rules:
                return
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            fired = None
            for rule in rules:
                if rule.fires(n):
                    fired = rule
                    break
            if fired is None:
                return
            self._injected[site] = self._injected.get(site, 0) + 1
        exc_name = fired.exc.__name__
        telemetry.counter_add("faults.injected", 1, site=site, exc=exc_name,
                              **attrs)
        telemetry.event("fault", site, self._injected.get(site),
                        {"exc": exc_name, **attrs})
        raise fired.exc(f"injected fault at {site} (call {n})")

    # -- introspection / test control ----------------------------------------
    def active(self) -> bool:
        with self._lock:
            self._sync()
            return bool(self._rules)

    def counts(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {"calls": dict(self._calls),
                    "injected": dict(self._injected)}

    def reset(self):
        """Forget call history and force a reparse on next use."""
        with self._lock:
            self._src = None
            self._rules = {}
            self._calls.clear()
            self._injected.clear()


# -- module-level surface ----------------------------------------------------

def _reg() -> FaultRegistry:
    return FaultRegistry.instance()


def maybe_fail(site: str, **attrs):
    return _reg().maybe_fail(site, **attrs)


def active() -> bool:
    return _reg().active()


def counts() -> Dict[str, Dict[str, int]]:
    return _reg().counts()


def reset():
    return _reg().reset()


def configure(spec: Optional[str], seed: Optional[int] = None):
    """Install a fault spec (None/'' disables) + optional seed, resetting
    call history — the programmatic twin of FLAGS_fault_spec /
    PT_FAULT_SPEC."""
    _flags.set_flags({"fault_spec": spec or ""})
    if seed is not None:
        _flags.set_flags({"fault_seed": int(seed)})
    _reg().reset()
    _reg().active()   # eager validation: a bad spec raises HERE
