"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""

from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: dict[str, int] = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return f"{self.prefix}{key}_{tmp}"


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


@contextlib.contextmanager
def guard(new_generator: str | UniqueNameGenerator | None = None):
    """Swap in a fresh generator (used by tests for reproducible names)."""
    global _generator
    old = _generator
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    _generator = new_generator or UniqueNameGenerator()
    try:
        yield
    finally:
        _generator = old


def switch(new_generator: UniqueNameGenerator | None = None):
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old
