"""IR pass framework — program-rewriting optimization passes.

Capability mirror of the reference's ir::Pass stack (framework/ir/pass.h:40,
pass registry, GraphPatternDetector graph_pattern_detector.cc, and the
fusion passes fc_fuse_pass / multihead_matmul_fuse_pass /
fuse_elewise_add_act_pass). Re-designed for the XLA substrate: generic
elementwise/matmul fusion is XLA's job, so the passes that remain are the
SEMANTIC rewrites XLA cannot do — swapping an op chain for a Pallas kernel
(attention), collapsing API-level op pairs (mul+add → fc), and stripping
test-time no-ops (dropout).

A Pass maps Program → Program (mutating in place and returning it).
Passes here operate on the op list of block 0 — the same data the
executor compiles — so anything a pass rewrites is exactly what jit sees.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from . import telemetry
from .ir import OpDesc, Program

PassFn = Callable[[Program], Program]

_PASS_REGISTRY: Dict[str, PassFn] = {}


def register_pass(name: str):
    def deco(fn: PassFn) -> PassFn:
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def get_pass(name: str) -> PassFn:
    if name not in _PASS_REGISTRY:
        raise KeyError(f"unknown pass '{name}'; have {sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name]


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def _referenced_names(program: Program) -> Set[str]:
    """Every var name any op references — io slots plus (over-
    approximately) any string reachable through attr values, so name
    lists carried in attrs (control-flow input_names/carry_names,
    fusion_group sub_ops io) keep their vars alive."""
    from .verify import VerifyContext

    return VerifyContext(program).referenced


def _prune_orphaned_vars(program: Program, before: Set[str],
                         keep: Set[str]) -> int:
    """Drop non-persistable VarDescs a pass just orphaned: referenced
    before the pass, referenced by nothing after it (the classic fusion
    leak — the consumed intermediate's desc left behind). Only vars the
    pass itself disconnected are touched; pre-existing unreferenced
    declarations (e.g. an unused data var that is somebody's feed) are
    left alone."""
    after = _referenced_names(program)
    pruned = 0
    for blk in program.blocks:
        for name in [n for n in blk.vars
                     if n in before and n not in after and n not in keep
                     and not blk.vars[n].desc.persistable]:
            del blk.vars[name]
            pruned += 1
    if pruned:
        program._bump_version()
        telemetry.counter_add("verifier.pruned_vars", pruned)
    return pruned


def apply_passes(program: Program, names: List[str], scope=None,
                 feed_names=None, fetch_names=None,
                 verify: Optional[bool] = None) -> Program:
    """Apply passes in order, verifying the program after each one.

    Value-level passes (weight-folding fusions like conv+BN) declare a
    `scope` parameter and receive the parameter store; pure structural
    passes keep the Program -> Program signature.

    After every pass the static verifier (core/verify.py) re-checks the
    program — structure, dataflow, hazards, donation safety — so
    pass-introduced corruption raises a ProgramVerifyError NAMING the
    offending pass instead of surfacing as a pjit error later; VarDescs
    the pass orphaned are pruned first (counted in
    verifier.pruned_vars). feed_names/fetch_names sharpen the dataflow
    checks when the caller knows them (the predictor does). verify=None
    follows FLAGS_verify_passes (default on)."""
    import inspect

    from .flags import flag as _flag

    if verify is None:
        verify = bool(_flag("verify_passes"))
    keep = set(feed_names or ()) | {str(f) for f in (fetch_names or ())}
    for n in names:
        fn = get_pass(n)
        before = _referenced_names(program) if verify else None
        if "scope" in inspect.signature(fn).parameters:
            program = fn(program, scope=scope)
        else:
            program = fn(program)
        if verify:
            from .verify import verify_program

            _prune_orphaned_vars(program, before, keep)
            verify_program(program, feed_names=feed_names,
                           fetch_names=fetch_names, scope=scope,
                           context=f"after pass '{n}'")
    return program


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _single_consumer_map(ops: List[OpDesc]) -> Dict[str, List[OpDesc]]:
    consumers: Dict[str, List[OpDesc]] = {}
    for op in ops:
        for name in op.input_names():
            consumers.setdefault(name, []).append(op)
    return consumers


def _producer_map(ops: List[OpDesc]) -> Dict[str, OpDesc]:
    prod: Dict[str, OpDesc] = {}
    for op in ops:
        for name in op.output_names():
            prod[name] = op
    return prod


def _out(op: OpDesc, slot: str) -> Optional[str]:
    v = op.outputs.get(slot)
    return v[0] if v else None


def _in(op: OpDesc, slot: str) -> Optional[str]:
    v = op.inputs.get(slot)
    return v[0] if v else None


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------

@register_pass("delete_dropout_pass")
def delete_dropout_pass(program: Program) -> Program:
    """Strip is_test dropout ops (identity at inference) by rewiring their
    consumers — reference: simplify_with_basic_ops_pass (dropout removal)."""
    block = program.global_block()
    rename: Dict[str, str] = {}
    kept: List[OpDesc] = []
    for op in block.ops:
        # apply pending renames to inputs first
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]
        if op.type == "dropout" and bool(op.attrs.get("is_test", False)) and \
                op.attrs.get("dropout_implementation",
                             "upscale_in_train") == "upscale_in_train":
            rename[_out(op, "Out")] = _in(op, "X")
            continue
        kept.append(op)
    block.ops = kept
    program._bump_version()
    return program


@register_pass("fc_fuse_pass")
def fc_fuse_pass(program: Program) -> Program:
    """mul/matmul_v2 + elementwise_add(bias) → one fc op
    (reference: ir/fc_fuse_pass.cc)."""
    block = program.global_block()
    consumers = _single_consumer_map(block.ops)
    fused_away = set()
    new_ops: List[OpDesc] = []
    for op in block.ops:
        if id(op) in fused_away:
            continue
        if op.type in ("mul", "matmul_v2") and not op.attrs.get("trans_x") \
                and not op.attrs.get("trans_y"):
            out = _out(op, "Out")
            cons = consumers.get(out, [])
            if len(cons) == 1 and cons[0].type == "elementwise_add":
                add = cons[0]
                bias_name = _in(add, "Y")
                bias_var = block.var(bias_name) \
                    if bias_name and block.has_var(bias_name) else None
                # only fuse a real bias: 1-D persistable parameter (the
                # reference fc_fuse_pass.cc requirement) — never a
                # residual-add of another activation tensor
                bias_ok = (bias_var is not None and bias_var.persistable
                           and len(bias_var.shape or ()) == 1)
                if bias_ok and _in(add, "X") == out and \
                        int(add.attrs.get("axis", -1)) in (-1, 1):
                    xname = _in(op, "X")
                    if op.type == "matmul_v2":
                        # batched matmul contracts only the last dim:
                        # flatten everything before it
                        xv = block.var(xname) if block.has_var(xname) else None
                        ncol = (len(xv.shape) - 1) if xv is not None and \
                            xv.shape and len(xv.shape) > 1 else 1
                    else:
                        ncol = op.attrs.get("x_num_col_dims", 1)
                    new_ops.append(OpDesc(
                        "fc",
                        {"Input": [xname], "W": [_in(op, "Y")],
                         "Bias": [_in(add, "Y")]},
                        {"Out": [_out(add, "Out")]},
                        {"in_num_col_dims": ncol}))
                    fused_away.add(id(add))
                    continue
        new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program


@register_pass("conv_bn_fuse_pass")
def conv_bn_fuse_pass(program: Program, scope=None) -> Program:
    """conv2d → batch_norm(is_test) folded into one conv + bias add
    (reference: ir/conv_bn_fuse_pass.cc). BN at inference is an affine
    per-channel transform: y = k*conv(x) + c with k = scale/sqrt(var+eps)
    and c = bias - mean*k, so the conv filter absorbs k (OIHW out-channel
    axis) and c becomes a bias. Weight folding needs parameter VALUES —
    the pass requires the predictor scope and is a no-op without one."""
    if scope is None:
        return program
    import numpy as np

    from . import unique_name

    block = program.global_block()
    consumers = _single_consumer_map(block.ops)
    dead = set()
    new_ops: List[OpDesc] = []
    for op in block.ops:
        if id(op) in dead:
            continue
        if op.type == "conv2d" and int(op.attrs.get("groups", 1) or 1) == 1:
            out = _out(op, "Output")
            cons = consumers.get(out, [])
            # sync_batch_norm folds identically: its is_test path uses
            # only running stats (no cross-rank reduction)
            bn = cons[0] if len(cons) == 1 and \
                cons[0].type in ("batch_norm", "sync_batch_norm") else None
            if bn is not None and (bool(bn.attrs.get("is_test", False))
                                   or bool(bn.attrs.get(
                                       "use_global_stats", False))):
                names = {s: _in(bn, s)
                         for s in ("Scale", "Bias", "Mean", "Variance")}
                w_name = _in(op, "Filter")
                vals = {s: scope.find_var(n) for s, n in names.items()}
                w = scope.find_var(w_name)
                if w is not None and all(v is not None
                                         for v in vals.values()):
                    eps = float(bn.attrs.get("epsilon", 1e-5))
                    k = np.asarray(vals["Scale"], np.float32) / np.sqrt(
                        np.asarray(vals["Variance"], np.float32) + eps)
                    new_w = (np.asarray(w, np.float32)
                             * k[:, None, None, None]).astype(
                                 np.asarray(w).dtype)
                    new_b = (np.asarray(vals["Bias"], np.float32)
                             - np.asarray(vals["Mean"], np.float32) * k)
                    wf_name = unique_name.generate(w_name + "@bn_fused")
                    bf_name = unique_name.generate(w_name + "@bn_bias")
                    wv = block.var(w_name)
                    block.create_parameter(name=wf_name,
                                           shape=tuple(wv.shape),
                                           dtype=str(wv.dtype))
                    block.create_parameter(name=bf_name,
                                           shape=(len(new_b),),
                                           dtype="float32")
                    scope.set(wf_name, new_w)
                    scope.set(bf_name, new_b.astype(np.float32))
                    conv_out = block.create_var(
                        name=unique_name.generate(out + "@fused"),
                        shape=tuple(block.var(out).shape)
                        if block.has_var(out) else None)
                    fused_conv = OpDesc(
                        "conv2d",
                        {"Input": op.inputs["Input"], "Filter": [wf_name]},
                        {"Output": [conv_out.name]}, dict(op.attrs))
                    y = _out(bn, "Y")
                    new_ops.append(fused_conv)
                    new_ops.append(OpDesc(
                        "elementwise_add",
                        {"X": [conv_out.name], "Y": [bf_name]},
                        {"Out": [y]}, {"axis": 1}))
                    dead.add(id(bn))
                    continue
        new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program


@register_pass("embedding_eltwise_layernorm_fuse_pass")
def embedding_eltwise_layernorm_fuse_pass(program: Program) -> Program:
    """N x lookup_table(_v2) summed then layer_norm'd (the BERT embedding
    stack) -> ONE fused_embedding_eltwise_layernorm op (reference:
    ir/embedding_eltwise_layernorm_fuse_pass.cc driving
    fused/fused_embedding_eltwise_layernorm_op.cu)."""
    block = program.global_block()
    consumers = _single_consumer_map(block.ops)
    producer = _producer_map(block.ops)
    dead = set()
    new_ops: List[OpDesc] = []

    def as_lookup(name):
        # v2 only (v1 squeezes a trailing ids dim the fused op doesn't);
        # padding_idx zeroes rows in the unfused op — the fused lowering
        # has no mask, so those lookups must stay unfused
        op = producer.get(name)
        if op is not None and op.type == "lookup_table_v2" and \
                int(op.attrs.get("padding_idx", -1)) < 0 and \
                len(consumers.get(name, [])) == 1:
            return op
        return None

    for op in block.ops:
        if id(op) in dead:
            continue
        # anchor on layer_norm; walk the add tree beneath it
        if op.type == "layer_norm" and \
                int(op.attrs.get("begin_norm_axis", 1)) == 2:
            chain = []
            ids, embs = [], []

            def collect(name):
                lk = as_lookup(name)
                if lk is not None:
                    ids.append(lk.inputs["Ids"][0])
                    embs.append(lk.inputs["W"][0])
                    chain.append(lk)
                    return True
                add = producer.get(name)
                if add is not None and add.type == "elementwise_add" and \
                        len(consumers.get(name, [])) == 1:
                    if collect(_in(add, "X")) and collect(_in(add, "Y")):
                        chain.append(add)
                        return True
                return False

            has_affine = bool(op.inputs.get("Scale")) and \
                bool(op.inputs.get("Bias"))
            if has_affine and collect(_in(op, "X")) and len(ids) >= 2:
                # scale=False/shift=False layer_norms are left unfused —
                # the fused lowering requires the affine pair
                new_ops.append(OpDesc(
                    "fused_embedding_eltwise_layernorm",
                    {"Ids": list(ids), "Embs": list(embs),
                     "Scale": op.inputs["Scale"],
                     "Bias": op.inputs["Bias"]},
                    {"Out": [_out(op, "Y")]},
                    {"epsilon": op.attrs.get("epsilon", 1e-5)}))
                dead.update(id(o) for o in chain)
                continue
        new_ops.append(op)
    block.ops = [o for o in new_ops if id(o) not in dead]
    program._bump_version()
    return program


@register_pass("fuse_elewise_add_act_pass")
def fuse_elewise_add_act_pass(program: Program) -> Program:
    """elementwise_add -> relu/gelu/tanh/sigmoid becomes one
    fused_elemwise_activation op (reference: ir/fuse_elewise_add_act_pass.cc
    — there it picks a fused CUDA kernel; here the compound op keeps the
    graph smaller and XLA fuses the arithmetic either way)."""
    block = program.global_block()
    consumers = _single_consumer_map(block.ops)
    dead = set()
    new_ops: List[OpDesc] = []
    acts = ("relu", "gelu", "tanh", "sigmoid")
    for op in block.ops:
        if id(op) in dead:
            continue
        if op.type == "elementwise_add" and \
                int(op.attrs.get("axis", -1)) == -1:
            out = _out(op, "Out")
            cons = consumers.get(out, [])
            if len(cons) == 1 and cons[0].type in acts:
                act = cons[0]
                # carry the act op's attrs so e.g. gelu(approximate=...)
                # keeps its exact numerics through the fuse
                fattrs = dict(act.attrs)
                fattrs.pop("op_role", None)
                fattrs["functor_list"] = ["elementwise_add", act.type]
                new_ops.append(OpDesc(
                    "fused_elemwise_activation",
                    {"X": op.inputs["X"], "Y": op.inputs["Y"]},
                    {"Out": [_out(act, "Out")],
                     "IntermediateOut": [out]},
                    fattrs))
                dead.add(id(act))
                continue
        new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program


@register_pass("multihead_attention_fuse_pass")
def multihead_attention_fuse_pass(program: Program) -> Program:
    """matmul(QK^T, alpha) [+ bias] → softmax [→ dropout] → matmul(·V)
    becomes one flash_attention op backed by the Pallas kernel
    (reference: ir/multihead_matmul_fuse_pass.cc — there a CUDA fused
    kernel; here the Pallas flash kernel, ops/attention_ops.py)."""
    block = program.global_block()
    consumers = _single_consumer_map(block.ops)
    dead = set()
    new_ops: List[OpDesc] = []

    def only_consumer(name, op_type):
        cons = [c for c in consumers.get(name, [])]
        if len(cons) == 1 and cons[0].type == op_type:
            return cons[0]
        return None

    for op in block.ops:
        if id(op) in dead:
            continue
        # anchor: the scores matmul q @ k^T
        if op.type == "matmul" and op.attrs.get("transpose_Y") and \
                not op.attrs.get("transpose_X"):
            q, k = _in(op, "X"), _in(op, "Y")
            scale = float(op.attrs.get("alpha", 1.0))
            scores = _out(op, "Out")
            bias = None
            cur = op
            nxt = only_consumer(scores, "elementwise_add")
            if nxt is not None:
                bias = _in(nxt, "Y") if _in(nxt, "X") == scores else _in(nxt, "X")
                scores = _out(nxt, "Out")
                cur = nxt
            sm = only_consumer(scores, "softmax")
            if sm is None or int(sm.attrs.get("axis", -1)) != -1:
                new_ops.append(op)
                continue
            probs = _out(sm, "Out")
            chain = [op] if cur is op else [op, cur]
            chain.append(sm)
            drop = only_consumer(probs, "dropout")
            if drop is not None and bool(drop.attrs.get("is_test", False)) \
                    and drop.attrs.get("dropout_implementation",
                                       "upscale_in_train") == \
                    "upscale_in_train":  # downgrade_in_infer scales at test
                probs = _out(drop, "Out")
                chain.append(drop)
            ctx_mm = only_consumer(probs, "matmul")
            if ctx_mm is None or _in(ctx_mm, "X") != probs or \
                    ctx_mm.attrs.get("transpose_X") or \
                    ctx_mm.attrs.get("transpose_Y") or \
                    float(ctx_mm.attrs.get("alpha", 1.0)) != 1.0:
                new_ops.append(op)
                continue
            v = _in(ctx_mm, "Y")
            chain.append(ctx_mm)
            inputs = {"Q": [q], "K": [k], "V": [v]}
            if bias is not None:
                inputs["Bias"] = [bias]
            new_ops.append(OpDesc("flash_attention", inputs,
                                  {"Out": [_out(ctx_mm, "Out")]},
                                  {"scale": scale, "causal": False}))
            dead.update(id(o) for o in chain if o is not op)
            continue
        new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program


@register_pass("fuse_bn_act_pass")
def fuse_bn_act_pass(program: Program) -> Program:
    """TRAINING-time batch_norm(+elementwise_add)+relu → one
    fused_bn_add_act op (reference: ir/fuse_bn_act_pass.cc and
    fuse_bn_add_act_pass.cc installing fused_bn_activation /
    fused_bn_add_activation). Run BEFORE append_backward: the fused op's
    pinned-residual custom_vjp then owns the whole backward segment.

    Patterns (Y single-consumed at every hop, training-mode BN only):
      batch_norm → relu
      batch_norm → elementwise_add(± either operand order) → relu
    """
    block = program.global_block()
    consumers = _single_consumer_map(block.ops)
    dead = set()
    # fused op INSERTS at the relu's position (the pattern's last op) —
    # a residual Z may be produced between the bn and the relu (the
    # shortcut branch), so replacing at the bn's position would read Z
    # before its producer runs
    fused_at: Dict[int, OpDesc] = {}
    for op in block.ops:
        if op.type != "batch_norm" or op.attrs.get("is_test", False) \
                or op.attrs.get("use_global_stats", False):
            continue
        y = _out(op, "Y")
        cons = consumers.get(y, [])
        nxt = cons[0] if len(cons) == 1 else None
        if nxt is None or id(nxt) in dead:
            continue            # (dead: chain absorbed by an earlier
        z = None                # match, e.g. the OTHER bn feeding the
        add_op = None           # same residual add)
        if nxt.type == "elementwise_add" and \
                int(nxt.attrs.get("axis", -1)) in (-1, 0):
            other = _in(nxt, "Y") if _in(nxt, "X") == y else _in(nxt, "X")
            add_out = _out(nxt, "Out")
            cons2 = consumers.get(add_out, [])
            relu = cons2[0] if len(cons2) == 1 and \
                cons2[0].type == "relu" and id(cons2[0]) not in dead \
                else None
            if relu is None:
                continue
            add_op, z, nxt = nxt, other, relu
        if nxt.type != "relu":
            continue
        inputs = dict(op.inputs)
        if z is not None:
            inputs["Z"] = [z]
        outputs = dict(op.outputs)
        outputs["Y"] = [_out(nxt, "Out")]
        fused_at[id(nxt)] = OpDesc(
            "fused_bn_add_act", inputs, outputs,
            {**{k: v for k, v in op.attrs.items()}, "act": "relu"})
        dead.update((id(op), id(nxt)))
        if add_op is not None:
            dead.add(id(add_op))

    new_ops: List[OpDesc] = []
    for op in block.ops:
        if id(op) in fused_at:
            new_ops.append(fused_at[id(op)])
        elif id(op) not in dead:
            new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program


# Ops safe to pack into a fusion_group: pure elementwise lowerings with
# no sub-blocks, no collectives, no state. dropout IS included — the
# group lowering threads __step__/__axis_coords__ through, preserving
# per-step masks.
_FUSION_GROUP_OPS = frozenset({
    "relu", "relu6", "gelu", "tanh", "sigmoid", "exp", "log", "sqrt",
    "rsqrt", "square", "abs", "floor", "ceil", "round", "reciprocal",
    "softsign", "silu", "swish", "softplus", "logsigmoid", "sin", "cos",
    "erf", "sign", "leaky_relu", "elu", "hard_swish", "hard_sigmoid",
    "scale", "cast", "clip", "dropout",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow",
})


@register_pass("fusion_group_pass")
def fusion_group_pass(program: Program, min_size: int = 2) -> Program:
    """Pack maximal runs of consecutive elementwise ops into single
    fusion_group ops (reference: ir/fusion_group/ — there it NVRTC-
    compiles a CUDA kernel per subgraph via platform/device_code.cc; on
    the XLA substrate generic fusion is the compiler's job, so the win
    is DISPATCH: the interpreting executor jits and launches one
    composite instead of N ops, the per-op analog of the reference's
    per-kernel launch overhead).

    Grouping is order-preserving over block 0 (block op order is
    topological): a run extends while the op is whitelisted, shares the
    run's op_role, and touches no persistable vars. Outputs consumed
    only inside the run become internal; everything else (consumed
    later, or never — a potential fetch target) is exported."""
    from .registry import EMPTY_VAR

    block = program.global_block()
    persistable = {v.name for v in block.vars.values() if v.persistable}

    def groupable(op):
        if op.type not in _FUSION_GROUP_OPS:
            return False
        names = [n for ns in list(op.inputs.values()) +
                 list(op.outputs.values()) for n in ns]
        return not any(n == EMPTY_VAR or n in persistable for n in names)

    runs: List[List[OpDesc]] = []
    cur: List[OpDesc] = []
    cur_role = None
    for op in block.ops:
        role = int(op.attrs.get("op_role", 0))
        if groupable(op) and (not cur or role == cur_role):
            cur.append(op)
            cur_role = role
        else:
            if len(cur) >= min_size:
                runs.append(cur)
            cur = [op] if groupable(op) else []
            cur_role = role if cur else None
    if len(cur) >= min_size:
        runs.append(cur)
    if not runs:
        return program

    replacements: Dict[int, OpDesc] = {}
    dead = set()
    for run in runs:
        members = {id(op) for op in run}
        produced: List[str] = []
        produced_set = set()
        ext_in: List[str] = []
        for op in run:
            for ns in op.inputs.values():
                for n in ns:
                    if n not in produced_set and n not in ext_in:
                        ext_in.append(n)
            for ns in op.outputs.values():
                for n in ns:
                    if n not in produced_set:
                        produced.append(n)
                        produced_set.add(n)
        # Export EVERY produced var: the program carries no fetch ops, so
        # an intermediate whose only op-consumers sit inside the run can
        # still be somebody's fetch target (fetch_list / inference
        # fetch_names are metadata the pass cannot see). The compiled
        # executor DCEs unused outputs anyway; on the interp path the
        # extra buffers are the price of fetch-by-name correctness.
        ext_out = produced
        if not ext_out:
            continue
        sub_ops = [{"type": op.type,
                    "inputs": {s: list(ns) for s, ns in op.inputs.items()},
                    "outputs": {s: list(ns) for s, ns in op.outputs.items()},
                    "attrs": {k: v for k, v in op.attrs.items()
                              if k != "op_role"}}
                   for op in run]
        replacements[id(run[0])] = OpDesc(
            "fusion_group", {"X": ext_in}, {"Out": ext_out},
            {"sub_ops": sub_ops, "ext_in_names": ext_in,
             "ext_out_names": ext_out,
             "op_role": int(run[0].attrs.get("op_role", 0))})
        dead.update(members)

    new_ops: List[OpDesc] = []
    for op in block.ops:
        if id(op) in replacements:
            new_ops.append(replacements[id(op)])
        elif id(op) not in dead:
            new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    return program
