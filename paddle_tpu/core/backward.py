"""Program-level autodiff: append grad ops to the program.

Capability mirror of python/paddle/fluid/backward.py (`append_backward`:1275,
`_append_backward_ops_`:922, `gradients`:1864): walk forward ops in reverse,
ask each op's GradOpMaker for grad op-descs, insert `@GRAD` vars, sum
duplicated gradients, honour stop_gradient / no_grad_set.

Unlike `jax.grad` on user code, gradients here ARE ops in the program —
keeping the reference's semantics (distributed transpilers and
meta-optimizers rewrite grad ops; optimizer state updates are ops too).
The default grad op is the generic `__vjp_grad__` (registry.py) whose
lowering calls jax.vjp on the forward lowering; XLA CSE dedupes the
recomputed forward inside one compiled block.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import registry, unique_name
from .ir import Block, OpDesc, OpRole, Parameter, Program, Variable

GRAD_SUFFIX = "@GRAD"


def _grad_name(name: str) -> str:
    return name + GRAD_SUFFIX


# Ops that are never differentiated through.
_NON_DIFF_OPS = {
    "fill_constant", "gaussian_random", "uniform_random", "feed", "fetch",
    "save", "load", "accuracy", "auc", "print", "assign_value", "shape",
    "c_comm_init", "c_gen_unique_id", "truncated_gaussian_random",
    "randint", "iota", "one_hot", "argmax", "argmin", "equal", "not_equal",
    "less_than", "less_equal", "greater_than", "greater_equal",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "update_loss_scaling", "check_finite_and_unscale", "isfinite",
}


def _requires_grad_vars(block: Block, ops: List[OpDesc], no_grad: Set[str],
                        extra_leaves: Set[str] = frozenset()) -> Set[str]:
    """Forward-propagate the requires-grad property from trainable leaves."""
    req: Set[str] = set(extra_leaves) - no_grad
    for var in block.vars.values():
        if isinstance(var, Parameter) and var.trainable and var.name not in no_grad:
            req.add(var.name)
    for op in ops:
        if op.type in _NON_DIFF_OPS:
            continue
        if any(n in req for n in op.input_names()):
            for n in op.output_names():
                if n in no_grad:
                    continue
                v = block._find_var_recursive(n)
                if v is not None and v.stop_gradient:
                    continue
                req.add(n)
    return req


class _GradAccumulator:
    """Collects gradient contributions per forward var; emits `sum` ops when a
    var has fan-out >1 (reference: backward.py _addup_repetitive_outputs_)."""

    def __init__(self, block: Block):
        self.block = block
        self.contribs: Dict[str, List[str]] = {}
        self.final: Dict[str, str] = {}

    def new_contrib_name(self, var_name: str) -> str:
        lst = self.contribs.setdefault(var_name, [])
        base = _grad_name(var_name)
        name = base if not lst else f"{base}@RENAME@{len(lst)}"
        lst.append(name)
        return name

    def set_final(self, var_name: str, grad_name: str):
        self.final[var_name] = grad_name
        self.contribs.setdefault(var_name, []).append(grad_name)

    def finalize(self, var_name: str) -> Optional[str]:
        """Called when the op PRODUCING var_name is reached in the reverse
        walk — all consumers are already processed, so sum now."""
        if var_name in self.final:
            return self.final[var_name]
        lst = self.contribs.get(var_name, [])
        if not lst:
            return None
        if len(lst) == 1:
            self.final[var_name] = lst[0]
            return lst[0]
        out = _grad_name(var_name)
        if out in lst:  # avoid summing a name into itself
            renamed = f"{out}@RENAME@0x"
            src_var = self.block._find_var_recursive(out)
            self.block.create_var(name=renamed,
                                  shape=src_var.shape if src_var else None,
                                  dtype=src_var.dtype if src_var else "float32",
                                  stop_gradient=True)
            for op in reversed(self.block.ops):
                if out in op.output_names():
                    op._rename_output(out, renamed)
                    break
            lst = [renamed if n == out else n for n in lst]
        self.block.create_var(name=out, stop_gradient=True)
        self.block.append_op("sum", {"X": lst}, {"Out": [out]},
                             {"op_role": OpRole.Backward})
        self.final[var_name] = out
        return out


def _ensure_grad_var(block: Block, fwd_name: str, grad_name: str):
    if grad_name == registry.EMPTY_VAR or block.has_var(grad_name):
        return
    fwd = block._find_var_recursive(fwd_name)
    block.create_var(name=grad_name,
                     shape=fwd.shape if fwd is not None else None,
                     dtype=fwd.dtype if fwd is not None else "float32",
                     stop_gradient=True)


def append_backward(loss: Variable, parameter_list: Optional[Sequence] = None,
                    no_grad_set: Optional[Set[str]] = None,
                    callbacks=None, checkpoints=None,
                    _extra_leaves: Set[str] = frozenset()) -> List[Tuple[Parameter, Variable]]:
    """Append grad ops for `loss` and return [(param, grad_var), ...].

    Reference: python/paddle/fluid/backward.py:1275.
    """
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    loss_idx = None
    for i in range(len(block.ops) - 1, -1, -1):
        if loss.name in block.ops[i].output_names():
            loss_idx = i
            break
    if loss_idx is None:
        raise ValueError(f"loss var '{loss.name}' is not produced by any op")

    fwd_ops = block.ops[: loss_idx + 1]
    req = _requires_grad_vars(block, fwd_ops, no_grad, _extra_leaves)
    if loss.name not in req:
        raise ValueError(
            f"loss '{loss.name}' does not depend on any trainable parameter")

    acc = _GradAccumulator(block)
    with program._role_guard(OpRole.Backward):
        # d(loss)/d(loss) = 1
        loss_grad = _grad_name(loss.name)
        block.create_var(name=loss_grad, shape=loss.shape or (1,),
                         dtype=loss.dtype, stop_gradient=True)
        block.append_op(
            "fill_constant", {}, {"Out": [loss_grad]},
            {"shape": list(loss.shape or (1,)), "value": 1.0,
             "dtype": str(np.dtype(loss.dtype)),
             "op_role": OpRole.Backward | OpRole.Loss})
        acc.set_final(loss.name, loss_grad)

        for op in reversed(fwd_ops):
            if op.type in _NON_DIFF_OPS or op.is_optimize_op():
                continue
            opdef = registry.lookup(op.type)
            if opdef is None:
                continue
            # finalize output grads (all consumers already visited)
            out_grads: Dict[str, List[Optional[str]]] = {}
            any_grad = False
            for slot, names in op.outputs.items():
                gs = []
                for n in names:
                    g = acc.finalize(n) if n in req else None
                    gs.append(g)
                    any_grad = any_grad or (g is not None)
                out_grads[slot] = gs
            if not any_grad:
                continue
            # decide which input grads to produce
            in_grads: Dict[str, List[Optional[str]]] = {}
            for slot, names in op.inputs.items():
                if slot in (opdef.non_diff_inputs or ()):
                    in_grads[slot] = [None] * len(names)
                    continue
                gs = []
                for n in names:
                    if n in req and n not in no_grad:
                        gs.append(acc.new_contrib_name(n))
                    else:
                        gs.append(None)
                in_grads[slot] = gs
            if all(g is None for gs in in_grads.values() for g in gs):
                continue
            maker = opdef.grad_maker or registry.default_grad_maker
            grad_ops = maker(op, out_grads, in_grads)
            for gop in grad_ops:
                gop.attrs.setdefault("op_role", OpRole.Backward)
                for slot, names in gop.outputs.items():
                    for gn in names:
                        # map grad var desc from its forward var when derivable
                        fwd_guess = gn.split(GRAD_SUFFIX)[0]
                        _ensure_grad_var(block, fwd_guess, gn)
                for slot, names in gop.inputs.items():
                    for gn in names:
                        if gn != registry.EMPTY_VAR and not block.has_var(gn):
                            _ensure_grad_var(block, gn.split(GRAD_SUFFIX)[0], gn)
                block.ops.append(gop)
                program._bump_version()

    # assemble (param, grad) pairs
    if parameter_list is not None:
        params = [p if isinstance(p, Variable) else block.var(str(p))
                  for p in parameter_list]
    else:
        params = [p for p in block.all_parameters() if p.trainable]
    result = []
    # finalize leaf inputs requested via gradients() so fan-out sums are emitted
    for name in _extra_leaves:
        acc.finalize(name)
    for name, gname in acc.final.items():
        program.grad_var_map.setdefault(name, gname)
    for p in params:
        g = acc.finalize(p.name)
        if g is None:
            continue
        program.grad_var_map[p.name] = g
        gvar = block.var(g)
        # record param↔grad on the producing op (reference: op_role_var attr,
        # used by DP rewrites to place allreduce)
        for op in reversed(block.ops):
            if g in op.output_names():
                op.attrs.setdefault("op_role_var", []).extend([p.name, g])
                break
        result.append((p, gvar))
    return result


def gradients(targets: Sequence[Variable], inputs: Sequence[Variable],
              target_gradients: Optional[Sequence[Variable]] = None,
              no_grad_set: Optional[Set[str]] = None) -> List[Optional[Variable]]:
    """paddle.static.gradients — grads of targets wrt inputs.

    Reference: backward.py:1864 / calc_gradient:1728. Implemented by running
    append_backward on a summed scalar of targets when target_gradients is
    None; custom target grads seed the accumulator instead of fill 1.
    """
    if not targets:
        return []
    t0 = targets[0]
    block = t0.block
    if target_gradients is None and (t0.shape is None or int(np.prod([d for d in (t0.shape or (1,)) if d != -1])) != 1 or len(targets) > 1):
        from .. import layers

        total = None
        for t in targets:
            s = layers.reduce_sum(t)
            total = s if total is None else total + s
        t0 = total
    append_backward(t0, parameter_list=[], no_grad_set=no_grad_set,
                    _extra_leaves={iv.name for iv in inputs})
    out = []
    for iv in inputs:
        g = block.program.grad_var_map.get(iv.name)
        if g is None:
            gname = _grad_name(iv.name)
            g = gname if block.has_var(gname) else None
        out.append(block.var(g) if g else None)
    return out
