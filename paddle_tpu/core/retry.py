"""Generalized retry/backoff/deadline schedule — ONE implementation of
the "try again, but not forever" policy the robustness subsystems share.

Extracted from the PS RPC transport (distributed/ps/rpc.py, PR 2), whose
inline loop owned the canonical semantics: exponential backoff doubling
from a base, +/-50% jitter so a retry storm decorrelates, a per-call
retry budget, and a wall-clock deadline that overrides everything —
checked BEFORE the budget, and clipping the last sleep so a schedule
never oversleeps its own deadline. The serving router
(paddle_tpu/serving/router.py) needs the same schedule for replica
failover, and the cluster controller for respawn pacing; copying the
loop three times is how the three copies drift, so the schedule lives
here and the call sites keep only what is genuinely theirs (sockets,
telemetry counter names, typed errors).

Deliberately mechanism-only: ``RetrySchedule`` decides *whether* and
*how long*; the caller performs the attempt, books its own telemetry
(``ps.rpc_retries`` / ``router.retries`` keep their existing names) and
raises its own typed errors, so rebasing a transport on this module is
behavior-preserving.

Usage::

    sched = RetryPolicy(max_retries=8, backoff=0.05, deadline=30.0).start()
    while True:
        try:
            return attempt(timeout=sched.remaining(default=None))
        except TransientError as e:
            outcome, delay = sched.note_failure()
            if outcome == DEADLINE:
                raise MyDeadlineError(...) from e
            if outcome == EXHAUSTED:
                raise MyError(...) from e
            time.sleep(delay)
"""

from __future__ import annotations

import random
import time
from typing import Optional, Tuple

# note_failure() outcomes
RETRY = "retry"          # sleep the returned delay, then attempt again
DEADLINE = "deadline"    # the wall-clock deadline elapsed — stop now
EXHAUSTED = "exhausted"  # the retry budget is spent — stop now


class RetryPolicy:
    """Immutable description of a retry schedule.

    max_retries: failed attempts beyond the first that may be retried
        (0 = one attempt, no retry).
    backoff: base seconds for exponential backoff — attempt k sleeps
        ~ backoff * 2**(k-1), jittered.
    deadline: total wall-clock budget in seconds for the whole schedule;
        None (or <= 0) disables it.
    max_delay: cap on a single backoff sleep.
    jitter: fractional +/- spread on each delay (0.5 -> uniform in
        [0.5x, 1.5x), the PR 2 transport's spread); 0 disables.
    """

    __slots__ = ("max_retries", "backoff", "deadline", "max_delay", "jitter")

    def __init__(self, max_retries: int = 8, backoff: float = 0.05,
                 deadline: Optional[float] = None, max_delay: float = 1.0,
                 jitter: float = 0.5):
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.deadline = float(deadline) if deadline and deadline > 0 else None
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)

    def start(self, rng: Optional[random.Random] = None) -> "RetrySchedule":
        """Open one schedule (one logical call's retry state)."""
        return RetrySchedule(self, rng=rng)

    def __repr__(self):
        return (f"RetryPolicy(max_retries={self.max_retries}, "
                f"backoff={self.backoff}, deadline={self.deadline}, "
                f"max_delay={self.max_delay}, jitter={self.jitter})")


class RetrySchedule:
    """Mutable per-call state: failed-attempt count + deadline clock.

    ``attempt`` is the number of failures noted so far — after the Nth
    ``note_failure`` it reads N, matching the attempt numbering the RPC
    transport always printed in its error messages.
    """

    __slots__ = ("policy", "attempt", "t0", "deadline_t", "_rng")

    def __init__(self, policy: RetryPolicy,
                 rng: Optional[random.Random] = None):
        self.policy = policy
        self.attempt = 0
        self.t0 = time.perf_counter()
        self.deadline_t = (self.t0 + policy.deadline
                           if policy.deadline is not None else None)
        self._rng = rng if rng is not None else random

    # -- clock queries --------------------------------------------------------
    def elapsed(self) -> float:
        return time.perf_counter() - self.t0

    def expired(self) -> bool:
        return (self.deadline_t is not None
                and time.perf_counter() >= self.deadline_t)

    def remaining(self, floor: float = 0.01,
                  default: Optional[float] = None) -> Optional[float]:
        """Seconds left on the deadline (never below ``floor``, so a
        just-expired schedule still gets a socket timeout that fails fast
        instead of a zero/negative one). ``default`` is returned when the
        schedule has no deadline — callers pass their static timeout."""
        if self.deadline_t is None:
            return default
        return max(self.deadline_t - time.perf_counter(), floor)

    # -- the decision ---------------------------------------------------------
    def note_failure(self) -> Tuple[str, float]:
        """Account one failed attempt and decide what happens next.

        Returns (RETRY, delay_seconds) when the caller should sleep and
        retry, (DEADLINE, 0.0) when the wall-clock budget is gone (checked
        first — a dead deadline wins over remaining retries), or
        (EXHAUSTED, 0.0) when the retry budget is spent. The delay is the
        jittered exponential backoff, capped at max_delay and clipped to
        whatever deadline remains."""
        self.attempt += 1
        now = time.perf_counter()
        if self.deadline_t is not None and now >= self.deadline_t:
            return DEADLINE, 0.0
        if self.attempt > self.policy.max_retries:
            return EXHAUSTED, 0.0
        delay = min(self.policy.backoff * (2 ** (self.attempt - 1)),
                    self.policy.max_delay)
        if self.policy.jitter:
            lo = 1.0 - self.policy.jitter
            delay *= lo + 2.0 * self.policy.jitter * self._rng.random()
        if self.deadline_t is not None:
            delay = min(delay, max(self.deadline_t - now, 0.0))
        return RETRY, delay

    def sleep_or_raise(self, exc_factory=None) -> None:
        """Convenience for plain loops: sleep the next backoff delay, or
        raise ``exc_factory(outcome, self)`` (default TimeoutError) when
        the schedule is done."""
        outcome, delay = self.note_failure()
        if outcome == RETRY:
            time.sleep(delay)
            return
        if exc_factory is not None:
            raise exc_factory(outcome, self)
        raise TimeoutError(
            f"retry schedule {outcome} after {self.attempt} attempts "
            f"({self.elapsed():.3f}s)")
