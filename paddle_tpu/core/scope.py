"""Scope: hierarchical name → value store.

Capability mirror of the reference Scope/Variable
(paddle/fluid/framework/scope.h:52, variable.h:26). Values here are
jax.Arrays (device-resident), numpy arrays, or opaque Python objects
(readers, comm handles — the reference's RAW var kind).
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Any, Dict, Iterator, Optional


class Scope:
    _uid_counter = itertools.count()

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self.parent = parent
        self.kids: list[Scope] = []
        # process-unique, never-reused identity for executor cache keys
        self.uid = next(Scope._uid_counter)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def drop_kids(self):
        self.kids.clear()

    def set(self, name: str, value: Any):
        self._vars[name] = value

    def find_var(self, name: str) -> Any:
        """Recursive lookup (reference: Scope::FindVar). Returns None if absent."""
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def erase(self, name: str):
        self._vars.pop(name, None)

    def local_var_names(self) -> list[str]:
        return list(self._vars)

    def items(self) -> Iterator[tuple[str, Any]]:
        return iter(self._vars.items())

    def __contains__(self, name: str) -> bool:
        return self.has_var(name)

    def __len__(self):
        return len(self._vars)

    def __bool__(self):
        # an empty Scope is still a scope — never falsy (guards against
        # `scope or global_scope()` silently swapping in the global scope)
        return True


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def reset_global_scope():
    global _global_scope
    _global_scope = Scope()
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    """Swap the global scope for a `with` region (reference:
    fluid.executor.scope_guard / paddle.static.scope_guard)."""
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield scope
    finally:
        _global_scope = old
