"""Static program verifier + dataflow lint over the op-desc IR.

Capability mirror of the reference's program-validation tier — per-op
InferShape/InferVarType (framework/operator.cc:1076, op_desc.cc
CheckAttrs), the ir::Graph sanity walks (framework/ir/graph_helper.cc
HasCircle / graph.cc VarDesc consistency), and the MLIR-style rule that
every pass leaves a verifiable module — re-designed for this repo's
dataclass IR: a Program is checked STATICALLY, before jit, so a
malformed program (a dangling input left by a fusion pass, a shape
mismatch, two unordered writes to one var) fails at build/compile time
with a typed, located error instead of an opaque pjit/XLA message at
dispatch — or a silent wrong answer under buffer donation.

Composable checks, each a registered function over a VerifyContext:

* ``structure``  — every op input/output resolves to a scope-visible
  VarDesc, the op type is registered with a lowering, and the attrs its
  lowering dereferences unconditionally (OpDef.required_attrs) are
  present. Recurses into attr-held sub-blocks (cond/while bodies) and
  fusion_group sub_ops.
* ``dataflow``   — def-before-use in program order (recursing into
  control-flow sub-blocks), dangling reads (a non-persistable var no op
  produces and nothing feeds), uninitialized persistable reads when a
  scope is given, statically-missing fetch targets, and dead VarDescs
  no op references (the classic fusion-pass leak) as warnings.
* ``hazards``    — write-after-write on one var where nothing observes
  the first write (a lost update: under any reordering — or a pass that
  assumes SSA-ish block order — the program's meaning is ambiguous).
* ``donation``   — donation-safety lint for the compiling executor:
  state vars (persistable ∧ written in block 0) are donated across
  ``run_steps`` scan iterations, so a feed that aliases a state var, or
  a sub-block write to an outer persistable (invisible to the
  executor's block-0 state analysis — the update is silently dropped),
  is flagged.
* ``shapes``     — static shape/dtype propagation reusing the op
  registry's lowerings under ``jax.eval_shape`` (the same single source
  of truth as build-time inference, ir.py:_infer_op_shapes): inputs are
  taken from the propagated environment (falling back to declared
  VarDescs), dynamic dims resolved by the two-sentinel substitution,
  and both a lowering that REJECTS its declared input shapes and an
  inferred-vs-declared output mismatch are violations. Opt-in
  (``infer_shapes=True``) — it re-traces every lowering, so the always-
  on pass/executor gates run the cheap pure-Python checks only.

Wired in three places: ``core.passes.apply_passes`` verifies after
every pass (naming the offending pass), ``Executor`` gates compiles
behind ``FLAGS_verify_program``, and ``tools/graph_lint.py`` lints a
saved inference model / serialized program from the command line.
Telemetry: verifier.programs / verifier.checks_run /
verifier.violations counters and the verifier.verify_ms timer
(rendered by tools/perf_report.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from . import registry, telemetry
from .ir import Block, OpDesc, Program
from .registry import EMPTY_VAR

# Names the runtime injects into every step env — never a dangling read.
_RUNTIME_VARS = frozenset(("@STEP_COUNTER@",))

# Op types whose lowerings touch the host (network/file IO) or otherwise
# cannot be abstractly traced — the shapes check treats their outputs as
# unknown instead of eval_shape'ing them (mirrors executor._PS_IO_TYPES).
_SHAPE_SKIP_TYPES = frozenset((
    "send", "recv", "send_barrier", "fetch_barrier", "listen_and_serv",
    "save", "load", "save_combine", "load_combine", "checkpoint_notify",
    "py_func", "print", "feed", "fetch"))


# ---------------------------------------------------------------------------
# violations and the typed error
# ---------------------------------------------------------------------------

@dataclass
class Violation:
    """One finding: which check fired, where, and why."""

    check: str                    # e.g. "dangling_input"
    severity: str                 # "error" | "warning"
    block_idx: int
    op_idx: int                   # -1 for block-level findings
    op_type: str                  # "" for block-level findings
    var: str = ""
    message: str = ""

    def format(self) -> str:
        # clickable-style location prefix, program:block:op like file:line
        loc = f"program:block{self.block_idx}"
        if self.op_idx >= 0:
            loc += f":op{self.op_idx}"
        what = f" '{self.op_type}'" if self.op_type else ""
        var = f" var '{self.var}':" if self.var else ""
        return (f"{loc}: [{self.check}/{self.severity}]{what}:{var} "
                f"{self.message}")


class ProgramVerifyError(RuntimeError):
    """A program failed static verification.

    Carries the full violation list plus (block_idx, op_idx, op_type,
    check) of the first error for programmatic handling. Deliberately a
    plain RuntimeError subclass: it names a PROGRAMMING error, so
    ElasticRunner.RECOVERABLE (typed transport errors only) must never
    swallow it into a checkpoint-restart loop.
    """

    def __init__(self, violations: Sequence[Violation], context: str = ""):
        self.violations = list(violations)
        self.context = context
        errors = [v for v in self.violations if v.severity == "error"]
        first = errors[0] if errors else (
            self.violations[0] if self.violations else None)
        self.check = first.check if first else ""
        self.block_idx = first.block_idx if first else -1
        self.op_idx = first.op_idx if first else -1
        self.op_type = first.op_type if first else ""
        head = f"program verification failed"
        if context:
            head += f" ({context})"
        head += (f": {len(errors)} error(s), "
                 f"{len(self.violations) - len(errors)} warning(s)")
        lines = [head] + ["  " + v.format() for v in self.violations]
        super().__init__("\n".join(lines))


@dataclass
class VerifyResult:
    violations: List[Violation] = field(default_factory=list)
    checks_run: Tuple[str, ...] = ()
    elapsed_ms: float = 0.0
    context: str = ""

    @property
    def errors(self) -> List[Violation]:
        return [v for v in self.violations if v.severity == "error"]

    @property
    def warnings(self) -> List[Violation]:
        return [v for v in self.violations if v.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_if_error(self):
        if self.errors:
            raise ProgramVerifyError(self.violations, context=self.context)
        return self


# ---------------------------------------------------------------------------
# context shared by the checks
# ---------------------------------------------------------------------------

def _attr_blocks(op: OpDesc) -> List[Block]:
    """Blocks held in the op's attrs (cond true/false, while cond/body,
    block_call sub_block, run_program's program blocks)."""
    out: List[Block] = []

    def scan(val):
        if isinstance(val, Block):
            out.append(val)
        elif isinstance(val, Program):
            out.extend(val.blocks)
        elif isinstance(val, dict):
            for v in val.values():
                scan(v)
        elif isinstance(val, (list, tuple)):
            for v in val:
                scan(v)

    for val in (op.attrs or {}).values():
        scan(val)
    return out


def _string_refs(val, out: Set[str]):
    """Collect every string reachable through list/tuple/dict attr values
    (control-flow name lists, fusion_group sub_ops io names). Over-
    approximates on purpose: a name mentioned anywhere in an attr counts
    as referenced, so destructive consumers (var pruning) stay safe."""
    if isinstance(val, str):
        out.add(val)
    elif isinstance(val, dict):
        for v in val.values():
            _string_refs(v, out)
    elif isinstance(val, (list, tuple)):
        for v in val:
            _string_refs(v, out)


class VerifyContext:
    """Program + optional runtime knowledge (feeds/fetches/scope),
    with the block walk and per-block io tables precomputed once."""

    def __init__(self, program: Program, feed_names=None, fetch_names=None,
                 scope=None):
        self.program = program
        self.feed_names: Optional[Set[str]] = (
            set(feed_names) if feed_names is not None else None)
        self.fetch_names: List[str] = list(fetch_names or [])
        self.scope = scope
        self.scope_names: Optional[Set[str]] = None
        if scope is not None:
            names: Set[str] = set()
            s = scope
            while s is not None:
                names.update(s.local_var_names())
                s = getattr(s, "parent", None)
            self.scope_names = names
        # blocks: program.blocks plus attr-held blocks (a cloned program's
        # control-flow ops hold deepcopied blocks that are NOT in
        # program.blocks — those are what the lowerings execute)
        self.blocks: List[Block] = []
        seen: Set[int] = set()
        pending = list(program.blocks)
        while pending:
            blk = pending.pop(0)
            if id(blk) in seen or not isinstance(blk, Block):
                continue
            seen.add(id(blk))
            self.blocks.append(blk)
            for op in blk.ops:
                pending.extend(_attr_blocks(op))
        # all names any op (or op attr) references, program-wide
        self.referenced: Set[str] = set(self.fetch_names)
        for blk in self.blocks:
            for op in blk.ops:
                self.referenced.update(n for n in op.input_names())
                self.referenced.update(n for n in op.output_names())
                for val in (op.attrs or {}).values():
                    if not isinstance(val, (Block, Program)):
                        _string_refs(val, self.referenced)

    # -- helpers -------------------------------------------------------------
    def resolve(self, block: Block, name: str):
        return block._find_var_recursive(name)

    def block_writers(self, block: Block) -> Dict[str, List[int]]:
        writers: Dict[str, List[int]] = {}
        for i, op in enumerate(block.ops):
            for n in op.output_names():
                if n != EMPTY_VAR:
                    writers.setdefault(n, []).append(i)
        return writers

    def block_readers(self, block: Block) -> Dict[str, List[int]]:
        readers: Dict[str, List[int]] = {}
        for i, op in enumerate(block.ops):
            for n in op.input_names():
                if n != EMPTY_VAR:
                    readers.setdefault(n, []).append(i)
        return readers

    def is_external(self, name: str) -> bool:
        """Name satisfiable from outside the program at run time."""
        if self.feed_names is not None and name in self.feed_names:
            return True
        if self.scope_names is not None and name in self.scope_names:
            return True
        return name in _RUNTIME_VARS


# ---------------------------------------------------------------------------
# check registry
# ---------------------------------------------------------------------------

CheckFn = Callable[[VerifyContext], List[Violation]]

_CHECKS: Dict[str, CheckFn] = {}

# checks cheap enough to run on every pass application / executor gate
DEFAULT_CHECKS = ("structure", "dataflow", "hazards", "donation")


def register_check(name: str):
    def deco(fn: CheckFn) -> CheckFn:
        _CHECKS[name] = fn
        return fn

    return deco


def registered_checks() -> List[str]:
    return sorted(_CHECKS)


# ---------------------------------------------------------------------------
# structure
# ---------------------------------------------------------------------------

@register_check("structure")
def check_structure(ctx: VerifyContext) -> List[Violation]:
    vios: List[Violation] = []
    for blk in ctx.blocks:
        for oi, op in enumerate(blk.ops):
            opdef = registry.lookup(op.type)
            if opdef is None or opdef.forward is None:
                vios.append(Violation(
                    "unregistered_op", "error", blk.idx, oi, op.type,
                    message="op type has no registered lowering"))
            else:
                for a in opdef.required_attrs:
                    if a not in op.attrs:
                        vios.append(Violation(
                            "missing_attr", "error", blk.idx, oi, op.type,
                            var=a,
                            message=f"required attr '{a}' is absent "
                                    f"(the lowering dereferences it)"))
            for n in op.input_names():
                if n != EMPTY_VAR and ctx.resolve(blk, n) is None:
                    vios.append(Violation(
                        "dangling_input", "error", blk.idx, oi, op.type,
                        var=n,
                        message="reads a var with no VarDesc in any "
                                "scope-visible block"))
            for n in op.output_names():
                if n != EMPTY_VAR and ctx.resolve(blk, n) is None:
                    vios.append(Violation(
                        "undefined_output", "error", blk.idx, oi, op.type,
                        var=n,
                        message="writes a var with no VarDesc in any "
                                "scope-visible block"))
            if op.type == "fusion_group":
                for sub in op.attrs.get("sub_ops", []) or []:
                    st = sub.get("type") if isinstance(sub, dict) else None
                    if st is None or registry.lookup(st) is None:
                        vios.append(Violation(
                            "unregistered_op", "error", blk.idx, oi,
                            op.type, var=str(st),
                            message="fusion_group sub-op type is not "
                                    "registered"))
    return vios


# ---------------------------------------------------------------------------
# dataflow
# ---------------------------------------------------------------------------

@register_check("dataflow")
def check_dataflow(ctx: VerifyContext) -> List[Violation]:
    vios: List[Violation] = []
    for blk in ctx.blocks:
        writers = ctx.block_writers(blk)
        defined: Set[str] = set()
        for oi, op in enumerate(blk.ops):
            for n in op.input_names():
                if n == EMPTY_VAR or n in defined:
                    continue
                var = ctx.resolve(blk, n)
                if var is None:
                    continue          # structure already flagged it
                persistable = bool(var.desc.persistable)
                local_ws = writers.get(n)
                if local_ws:
                    # produced in this block, but only at a LATER index.
                    # Legit sources for the incoming value: the scope
                    # (persistable), the feed, an ancestor block's write
                    # (loop carries seeded by the parent control-flow
                    # op), or — for an in-place RMW op (increment,
                    # batch_norm stats) whose first writer is the
                    # reading op ITSELF — any of the above; flag only
                    # when none exist
                    if persistable or ctx.is_external(n) or \
                            _written_by_ancestor(ctx, blk, n):
                        continue
                    if local_ws[0] == oi:
                        # self-RMW with no visible source: only judge
                        # when we actually know the feeds
                        if ctx.feed_names is not None and \
                                blk.program is ctx.program:
                            vios.append(Violation(
                                "dangling_read", "error", blk.idx, oi,
                                op.type, var=n,
                                message="in-place op reads a var whose "
                                        "only producer is itself and "
                                        "nothing external provides it"))
                        continue
                    vios.append(Violation(
                        "def_after_use", "error", blk.idx, oi, op.type,
                        var=n,
                        message=f"read before its definition (first "
                                f"written by op {local_ws[0]} "
                                f"'{blk.ops[local_ws[0]].type}')"))
                    continue
                # external to this block: fine if persistable (scope),
                # produced by an ancestor block, fed, or runtime-injected
                if persistable:
                    if ctx.scope_names is not None \
                            and blk.program is ctx.program \
                            and n not in ctx.scope_names \
                            and not _written_by_ancestor(ctx, blk, n):
                        vios.append(Violation(
                            "uninitialized_read", "error", blk.idx, oi,
                            op.type, var=n,
                            message="persistable var is neither in the "
                                    "scope nor written earlier — did the "
                                    "startup program run?"))
                    continue
                if ctx.feed_names is None or blk.program is not ctx.program:
                    # no runtime knowledge (or a foreign attr-held
                    # sub-program with its own feed convention): can't
                    # judge external reads
                    continue
                if ctx.is_external(n) or _written_by_ancestor(ctx, blk, n):
                    continue
                vios.append(Violation(
                    "dangling_read", "error", blk.idx, oi, op.type, var=n,
                    message="non-persistable var has no producer and is "
                            "not fed — dangling read (pass-removed "
                            "producer?)"))
            for n in op.output_names():
                if n != EMPTY_VAR:
                    defined.add(n)
        # dead VarDescs: declared here, referenced by no op anywhere —
        # the droppings a fusion pass leaves behind
        for name, var in blk.vars.items():
            if name in ctx.referenced or var.desc.persistable:
                continue
            if ctx.feed_names is not None and name in ctx.feed_names:
                continue
            vios.append(Violation(
                "dead_var", "warning", blk.idx, -1, "", var=name,
                message="VarDesc is referenced by no op in any block "
                        "(leaked by a pass?)"))
    # fetch targets must be statically satisfiable from block 0
    if ctx.fetch_names:
        blk0 = ctx.program.global_block()
        produced = {n for op in blk0.ops for n in op.output_names()}
        for n in ctx.fetch_names:
            if n in produced or n in _RUNTIME_VARS:
                continue
            var = ctx.resolve(blk0, n)
            if var is not None and var.desc.persistable:
                continue
            if ctx.feed_names is not None and n in ctx.feed_names:
                continue
            if ctx.scope_names is not None and n in ctx.scope_names:
                continue
            vios.append(Violation(
                "missing_fetch", "error", 0, -1, "", var=n,
                message="fetch target is produced by no block-0 op and "
                        "is not fed/persistable"))
    return vios


def _written_by_ancestor(ctx: VerifyContext, block: Block, name: str) -> bool:
    blk = block.parent_block
    while blk is not None:
        for op in blk.ops:
            if name in op.output_names():
                return True
        blk = blk.parent_block
    return False


# ---------------------------------------------------------------------------
# hazards
# ---------------------------------------------------------------------------

@register_check("hazards")
def check_hazards(ctx: VerifyContext) -> List[Violation]:
    """Write-after-write with no intervening observer: op j overwrites
    op i's write and NOTHING (op j included) read the value in between.
    The first write is dead at best; at worst a pass that reorders
    independent-looking ops (or the donation machinery reusing the
    buffer) turns it into a wrong answer. Reference analog: the ir graph
    builder's write-dependency edges (graph.cc) that executors honour —
    this IR's program order is the only edge, so an unobserved double
    write means the edge never existed."""
    vios: List[Violation] = []
    for blk in ctx.blocks:
        writers = ctx.block_writers(blk)
        readers = ctx.block_readers(blk)
        for name, ws in writers.items():
            if len(ws) < 2:
                continue
            rs = readers.get(name, [])
            for i, j in zip(ws, ws[1:]):
                if any(i < r <= j for r in rs):
                    continue          # observed (or read-modify-write)
                vios.append(Violation(
                    "waw_hazard", "error", blk.idx, j,
                    blk.ops[j].type, var=name,
                    message=f"overwrites op {i} '{blk.ops[i].type}''s "
                            f"write with no read in between — unordered "
                            f"write-write hazard (lost update)"))
    return vios


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------

@register_check("donation")
def check_donation(ctx: VerifyContext) -> List[Violation]:
    vios: List[Violation] = []
    blk0 = ctx.program.global_block()
    state = set()
    for op in blk0.ops:
        for n in op.output_names():
            if n == EMPTY_VAR:
                continue
            var = ctx.resolve(blk0, n)
            if var is not None and var.desc.persistable:
                state.add(n)
    # (a) a feed aliasing donated state: env.update(state) then
    # env.update(feed) silently shadows the carried value, and under
    # run_steps the [k,...]-stacked feed is NOT a valid scan carry
    if ctx.feed_names:
        for n in sorted(ctx.feed_names & state):
            vios.append(Violation(
                "donated_feed_overlap", "error", 0, -1, "", var=n,
                message="fed var is also donated training state "
                        "(persistable + written by the block): the feed "
                        "shadows the carried value and breaks run_steps "
                        "scan donation"))
    # (b) sub-block writes to outer persistables: the compiling
    # executor's state analysis only sees block-0 writes, so the update
    # never reaches the scope (and the donated buffer may alias it)
    for blk in ctx.blocks:
        if blk is blk0 or blk.parent_idx < 0 and blk.idx == 0:
            continue
        for oi, op in enumerate(blk.ops):
            for n in op.output_names():
                if n == EMPTY_VAR or n in blk.vars:
                    continue
                var = ctx.resolve(blk, n)
                if var is not None and var.desc.persistable:
                    vios.append(Violation(
                        "sub_block_state_write", "warning", blk.idx, oi,
                        op.type, var=n,
                        message="sub-block writes an outer persistable "
                                "var — invisible to the executor's "
                                "block-0 state analysis; the update is "
                                "dropped (write it through a block-0 op "
                                "output instead)"))
    return vios


# ---------------------------------------------------------------------------
# static shape/dtype propagation
# ---------------------------------------------------------------------------

def _holds_block(op: OpDesc) -> bool:
    return any(isinstance(v, (Block, Program))
               for v in (op.attrs or {}).values())


@register_check("shapes")
def check_shapes(ctx: VerifyContext) -> List[Violation]:
    """Re-run build-time shape inference over the (possibly pass-
    rewritten) program: each op's registered lowering is traced with
    jax.eval_shape at the PROPAGATED input shapes (declared VarDescs
    seed the walk; dynamic -1 dims go through the same two-sentinel
    substitution as ir.Block._infer_op_shapes). A lowering that rejects
    its declared inputs is exactly the error pjit would throw at
    dispatch; an inferred-vs-declared output disagreement means some
    pass rewired shapes without updating descs."""
    import jax
    import numpy as np

    from .ir import _DYN_SENTINEL, _DYN_SENTINEL_B

    vios: List[Violation] = []
    for blk in ctx.blocks:
        # name -> (struct_a, struct_b) | None (= unknown, stop propagating)
        env: Dict[str, Any] = {}

        def mark_unknown(op):
            for n in op.output_names():
                if n != EMPTY_VAR:
                    env[n] = None

        for oi, op in enumerate(blk.ops):
            opdef = registry.lookup(op.type)
            if (opdef is None or opdef.forward is None
                    or opdef.skip_infer_shape or opdef.is_collective
                    or op.type in _SHAPE_SKIP_TYPES or _holds_block(op)):
                mark_unknown(op)
                continue
            structs_a: Dict[str, List[Any]] = {}
            structs_b: Dict[str, List[Any]] = {}
            has_dyn = False
            unknown = False
            for slot, names in op.inputs.items():
                la, lb = [], []
                for n in names:
                    if n == EMPTY_VAR:
                        la.append(None)
                        lb.append(None)
                        continue
                    pair = env.get(n, _ABSENT)
                    if pair is _ABSENT:
                        var = ctx.resolve(blk, n)
                        if var is None or var.shape is None:
                            unknown = True
                            break
                        dt = np.dtype(var.dtype)
                        sa = jax.ShapeDtypeStruct(
                            tuple(_DYN_SENTINEL if d == -1 else d
                                  for d in var.shape), dt)
                        sb = jax.ShapeDtypeStruct(
                            tuple(_DYN_SENTINEL_B if d == -1 else d
                                  for d in var.shape), dt)
                        if -1 in var.shape:
                            has_dyn = True
                        pair = (sa, sb)
                    elif pair is None:
                        unknown = True
                        break
                    else:
                        if pair[0].shape != pair[1].shape:
                            has_dyn = True
                    la.append(pair[0])
                    lb.append(pair[1])
                if unknown:
                    break
                structs_a[slot] = la
                structs_b[slot] = lb
            if unknown:
                mark_unknown(op)
                continue

            def eval_at(structs, _op=op, _fwd=opdef.forward):
                return jax.eval_shape(
                    lambda ins: _fwd(ins, dict(_op.attrs)), structs)

            try:
                out_a = eval_at(structs_a)
                out_b = eval_at(structs_b) if has_dyn else out_a
            except (TypeError, ValueError) as e:
                vios.append(Violation(
                    "shape_mismatch", "error", blk.idx, oi, op.type,
                    message=f"lowering rejects the declared input "
                            f"shapes: {type(e).__name__}: "
                            f"{str(e)[:300]}"))
                mark_unknown(op)
                continue
            except Exception:
                # untraceable for a non-shape reason (host callbacks,
                # opaque attrs): not this check's business
                telemetry.counter_add("verifier.shape_infer_skips", 1,
                                      op=op.type)
                mark_unknown(op)
                continue
            if not isinstance(out_a, dict):
                mark_unknown(op)
                continue
            for slot, names in op.outputs.items():
                va, vb = out_a.get(slot), out_b.get(slot)
                if va is None:
                    for n in names:
                        if n != EMPTY_VAR:
                            env[n] = None
                    continue
                if not isinstance(va, (list, tuple)):
                    va, vb = [va], [vb]
                for n, sa, sb in zip(names, va, vb):
                    if n == EMPTY_VAR:
                        continue
                    if sa is None or sb is None or \
                            len(sa.shape) != len(sb.shape):
                        env[n] = None
                        continue
                    env[n] = (sa, sb)
                    var = ctx.resolve(blk, n)
                    if var is None or var.shape is None:
                        continue
                    inferred = tuple(
                        -1 if da != db else da
                        for da, db in zip(sa.shape, sb.shape))
                    declared = tuple(var.shape)
                    if len(declared) != len(inferred) or any(
                            d != -1 and i != -1 and d != i
                            for d, i in zip(declared, inferred)):
                        vios.append(Violation(
                            "shape_mismatch", "error", blk.idx, oi,
                            op.type, var=n,
                            message=f"declared shape {declared} != "
                                    f"inferred {inferred}"))
                    elif np.dtype(var.dtype) != np.dtype(sa.dtype):
                        vios.append(Violation(
                            "dtype_mismatch", "error", blk.idx, oi,
                            op.type, var=n,
                            message=f"declared dtype "
                                    f"{np.dtype(var.dtype).name} != "
                                    f"inferred {np.dtype(sa.dtype).name}"))
    return vios


_ABSENT = object()


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def verify_program(program: Program, *, feed_names=None, fetch_names=None,
                   scope=None, checks: Optional[Sequence[str]] = None,
                   infer_shapes: bool = False, raise_on_error: bool = True,
                   context: str = "") -> VerifyResult:
    """Run the static checks over `program` and return a VerifyResult.

    feed_names/fetch_names/scope sharpen the dataflow checks (dangling
    reads, missing fetches, uninitialized persistables) — without them
    external inputs are assumed satisfiable. ``infer_shapes=True`` adds
    the eval_shape propagation check (one trace per op — opt in on hot
    paths). ``raise_on_error`` turns error-severity violations into a
    typed ProgramVerifyError.
    """
    names = list(checks) if checks is not None else list(DEFAULT_CHECKS)
    if infer_shapes and "shapes" not in names:
        names.append("shapes")
    ctx = VerifyContext(program, feed_names=feed_names,
                        fetch_names=fetch_names, scope=scope)
    t0 = time.perf_counter()
    violations: List[Violation] = []
    for name in names:
        fn = _CHECKS.get(name)
        if fn is None:
            raise KeyError(
                f"unknown verifier check '{name}'; have {registered_checks()}")
        violations.extend(fn(ctx))
    elapsed = (time.perf_counter() - t0) * 1e3
    telemetry.counter_add("verifier.programs", 1)
    telemetry.counter_add("verifier.checks_run", len(names))
    if violations:
        telemetry.counter_add("verifier.violations", len(violations),
                              context=context or None)
    telemetry.observe("verifier.verify_ms", round(elapsed, 3), kind="timer")
    result = VerifyResult(violations=violations, checks_run=tuple(names),
                          elapsed_ms=elapsed, context=context)
    if raise_on_error:
        result.raise_if_error()
    return result
