"""Operator registry: op type → {JAX lowering, grad maker}.

Capability mirror of the reference's OpRegistry / OpInfoMap
(paddle/fluid/framework/op_registry.h:75, op_info.h) re-designed for XLA:

* A kernel is a pure JAX-traceable function
  ``forward(inputs: {slot: [Array, ...]}, attrs) -> {slot: [Array, ...]}``
  — no per-Place kernel maps (framework/operator.cc:1141 ChooseKernel);
  XLA owns device placement and fusion.
* Gradients keep the reference's program-level semantics (grad ops are IR
  nodes built by a GradOpMaker, framework/grad_op_desc_maker.h) but the
  DEFAULT grad maker emits a single generic ``__vjp_grad__`` op whose
  lowering calls ``jax.vjp`` on the forward lowering. Hand-written grad ops
  are only needed where vjp recomputation hurts or semantics differ.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .ir import OpDesc

# Sentinel variable name meaning "no tensor" in an op's input list.
EMPTY_VAR = "@EMPTY@"

LoweringFn = Callable[[Dict[str, List[Any]], Dict[str, Any]], Dict[str, Any]]
# grad_maker(fwd_op, out_grads, in_grads) -> list of grad OpDescs.
#   out_grads: fwd output slot -> [grad var name or None, ...]
#   in_grads:  fwd input slot  -> [grad var name to produce or None, ...]
GradMakerFn = Callable[[OpDesc, Dict[str, List[Optional[str]]],
                        Dict[str, List[Optional[str]]]], List[OpDesc]]


@dataclass
class OpDef:
    type: str
    forward: Optional[LoweringFn] = None
    grad_maker: Optional[GradMakerFn] = None
    skip_infer_shape: bool = False
    # slots whose inputs are never differentiable (indices, masks, seeds)
    non_diff_inputs: tuple = ()
    # True for ops with side-band semantics the compiler must know about
    is_collective: bool = False
    # attrs the lowering dereferences unconditionally (attrs["..."]) — the
    # static verifier (core/verify.py) flags their absence at build/lint
    # time instead of a KeyError mid-trace (reference: OpProto required
    # attr checking, framework/op_desc.cc CheckAttrs)
    required_attrs: tuple = ()
    doc: str = ""


_REGISTRY: Dict[str, OpDef] = {}

# Every op type whose lowering has actually been INVOKED in this process
# (any path: executors, the SPMD oracle's jitted dispatch, dygraph
# trace_op, or a test calling the lowering directly). The suite-level
# execution-coverage gate (tests/conftest.py) asserts the registry
# against this set — a textual mention no longer counts as coverage
# (VERDICT r4 weak #4).
EXECUTED_OP_TYPES: set = set()


def _recorded(op_type: str, fn: LoweringFn) -> LoweringFn:
    import functools

    @functools.wraps(fn)
    def wrapper(ins, attrs):
        EXECUTED_OP_TYPES.add(op_type)
        return fn(ins, attrs)

    return wrapper


def register_op(type: str, *, grad_maker: Optional[GradMakerFn] = None,
                skip_infer_shape: bool = False, non_diff_inputs: tuple = (),
                is_collective: bool = False, required_attrs: tuple = (),
                doc: str = ""):
    """Decorator registering a forward lowering for `type`."""

    def deco(fn: LoweringFn) -> LoweringFn:
        od = _REGISTRY.get(type)
        if od is None:
            od = OpDef(type=type)
            _REGISTRY[type] = od
        od.forward = _recorded(type, fn)
        od.skip_infer_shape = skip_infer_shape
        od.non_diff_inputs = tuple(non_diff_inputs)
        od.is_collective = is_collective
        od.required_attrs = tuple(required_attrs)
        od.doc = doc or fn.__doc__ or ""
        if grad_maker is not None:
            od.grad_maker = grad_maker
        return fn

    return deco


def register_grad_maker(type: str):
    """Decorator attaching a custom GradOpMaker to an already/soon registered op."""

    def deco(fn: GradMakerFn) -> GradMakerFn:
        od = _REGISTRY.get(type)
        if od is None:
            od = OpDef(type=type)
            _REGISTRY[type] = od
        od.grad_maker = fn
        return fn

    return deco


def lookup(type: str) -> Optional[OpDef]:
    return _REGISTRY.get(type)


def get(type: str) -> OpDef:
    od = _REGISTRY.get(type)
    if od is None:
        raise KeyError(
            f"Operator '{type}' is not registered. Known ops: "
            f"{sorted(_REGISTRY)[:20]}... ({len(_REGISTRY)} total)")
    return od


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


def normalize_outputs(outs: Dict[str, Any]) -> Dict[str, List[Any]]:
    """Lowerings may return bare arrays per slot; normalise to lists."""
    norm = {}
    for k, v in outs.items():
        if isinstance(v, (list, tuple)):
            norm[k] = list(v)
        else:
            norm[k] = [v]
    return norm


# ---------------------------------------------------------------------------
# Generic vjp-based gradient
# ---------------------------------------------------------------------------

_IN_PREFIX = "In__"
_OG_PREFIX = "OG__"
_IG_PREFIX = "IG__"


def default_grad_maker(op: OpDesc, out_grads: Dict[str, List[Optional[str]]],
                       in_grads: Dict[str, List[Optional[str]]]) -> List[OpDesc]:
    """Build one ``__vjp_grad__`` op whose lowering is jax.vjp of the forward.

    Mirrors the role of DefaultGradOpMaker (framework/grad_op_desc_maker.h)
    without per-op hand-written grad kernels.
    """
    inputs: Dict[str, List[str]] = {}
    for slot, names in op.inputs.items():
        inputs[_IN_PREFIX + slot] = list(names)
    grad_out_slots = []
    for slot, names in op.outputs.items():
        gnames = out_grads.get(slot)
        if gnames is None:
            gnames = [None] * len(names)
        inputs[_OG_PREFIX + slot] = [g if g is not None else EMPTY_VAR for g in gnames]
        grad_out_slots.append(slot)
    outputs: Dict[str, List[str]] = {}
    want_slots = []
    for slot, gnames in in_grads.items():
        if gnames is None or all(g is None for g in gnames):
            continue
        outputs[_IG_PREFIX + slot] = [g if g is not None else EMPTY_VAR for g in gnames]
        want_slots.append(slot)
    if not outputs:
        return []
    grad_op = OpDesc("__vjp_grad__", inputs, outputs, {
        "fwd_type": op.type,
        "fwd_attrs": dict(op.attrs),
        "fwd_out_slots": list(op.outputs.keys()),
        "fwd_out_arity": {s: len(n) for s, n in op.outputs.items()},
    })
    return [grad_op]


def _is_inexact(x) -> bool:
    import jax.numpy as jnp

    return jnp.issubdtype(jnp.result_type(x), jnp.inexact)


@register_op("__vjp_grad__", skip_infer_shape=True,
             required_attrs=("fwd_type", "fwd_attrs"))
def _vjp_grad_lowering(ins: Dict[str, List[Any]], attrs: Dict[str, Any]):
    import jax
    import jax.numpy as jnp

    fwd_def = get(attrs["fwd_type"])
    # thread the runtime-injected attrs into the re-traced forward:
    # without __step__/__axis_coords__ a stochastic forward (dropout)
    # would re-trace with a DIFFERENT key than the forward op ran with —
    # the backward mask silently disagreeing with the forward mask
    fwd_attrs = dict(attrs["fwd_attrs"])
    for _k in ("__step__", "__axis_coords__"):
        if _k in attrs:
            fwd_attrs[_k] = attrs[_k]
    fwd_ins = {s[len(_IN_PREFIX):]: v for s, v in ins.items()
               if s.startswith(_IN_PREFIX)}

    def f(d):
        return normalize_outputs(fwd_def.forward(d, fwd_attrs))

    out_structs = jax.eval_shape(f, fwd_ins)
    # Assemble cotangents: provided grads where present, zeros elsewhere.
    cts: Dict[str, List[Any]] = {}
    for slot, structs in out_structs.items():
        ogs = ins.get(_OG_PREFIX + slot, [None] * len(structs))
        lst = []
        for i, s in enumerate(structs):
            og = ogs[i] if i < len(ogs) else None
            if og is not None:
                lst.append(jnp.asarray(og, dtype=s.dtype).reshape(s.shape))
            elif jnp.issubdtype(s.dtype, jnp.inexact):
                lst.append(jnp.zeros(s.shape, s.dtype))
            else:
                lst.append(np.zeros(s.shape, jax.dtypes.float0))
        cts[slot] = lst

    _, vjp_fn = jax.vjp(f, fwd_ins)
    (in_cts,) = vjp_fn(cts)

    outs: Dict[str, List[Any]] = {}
    for slot in fwd_ins:
        key = _IG_PREFIX + slot
        grads = in_cts.get(slot)
        if grads is None:
            continue
        fixed = []
        for g, x in zip(grads, fwd_ins[slot]):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                fixed.append(jnp.zeros(jnp.shape(x), jnp.result_type(x))
                             if _is_inexact(x) else jnp.zeros(jnp.shape(x), jnp.float32))
            else:
                fixed.append(g)
        outs[key] = fixed
    return outs
