"""Core: IR, registry, executors, autodiff, scope, compiler."""

from . import ir, registry, telemetry, types, unique_name  # noqa: F401
from .backward import append_backward, gradients  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .executor import ExecutionError, Executor, run_startup  # noqa: F401
from .ir import (Block, OpDesc, OpRole, Parameter, Program, VarDesc,  # noqa: F401
                 Variable, default_main_program, default_startup_program,
                 device_guard, in_dygraph_mode, program_guard)
from .scope import Scope, global_scope, reset_global_scope  # noqa: F401
from .verify import (ProgramVerifyError, VerifyResult,  # noqa: F401
                     Violation, verify_program)
from .types import (CPUPlace, CUDAPlace, Place, TPUPlace, VarType,  # noqa: F401
                    XLAPlace, convert_dtype, default_place)
