"""Distributed tracing — propagated per-request/per-step span contexts.

The reference's deepest observability tier is the profiler + CUPTI
timeline (platform/profiler.h rendered by tools/timeline.py): one
process, post-hoc, no causality across the RPC boundary. This module is
the Dapper-model complement: a *trace* is one logical request or
training step, made of *spans* (named, timed, nested operations) that
share a ``trace_id`` across threads and PROCESSES, so a serving request
can be followed client → HTTP server → admission queue → batch →
predictor, and a PS RPC call and its server-side handler render as one
causal tree in ``tools/trace_view.py``.

Model
-----
* A ``SpanContext`` is ``(trace_id, span_id)`` — 16-hex-digit ids. The
  context rides a ``contextvars.ContextVar``, so nesting follows Python
  call structure per thread and is safe under the serving/http thread
  pools.
* Sampling happens ONCE, at the root: ``span()`` outside any active
  context consults ``FLAGS_trace_sample_rate`` (0 disables — the
  default). A context existing ⟺ the trace is sampled; children and
  remote continuations never re-roll the dice (Dapper §3).
* Off ≈ zero cost: with rate 0 and no inherited context, ``span()``
  returns a shared no-op context manager — one ContextVar read and one
  flag lookup, no allocation, no clock reads, no record.
* Each finished sampled span is emitted as a ``kind:"span"`` telemetry
  JSONL record: ``value`` = duration ms, ``attrs`` = {trace, span,
  parent, start (epoch s), pid, tid, ...user attrs} — exactly what
  ``tools/trace_view.py`` needs to merge multi-process run logs into a
  chrome://tracing file.

Cross-process propagation
-------------------------
``inject()`` serialises the current context to ``"<trace>-<span>"``;
``span_from(header, name)`` opens a child span under that remote parent
(a propagated context is always honoured, even when the local sample
rate is 0 — the caller made the sampling decision). The PS RPC client
rides this on the frame's method field (surviving retries: the retry
loop sits INSIDE one client span, and the server's dedup cache replays
the reply without re-dispatching, so a retried+deduped frame still
yields exactly one handler span); the serving HTTP server accepts an
``X-Request-Id`` header as a forced trace id and returns the trace id
in the response.

Worker threads that serve a request long after ``submit()`` returned
(the serving engine's batch loop) cannot use the contextvar — they use
``record(name, parent, start, end)`` to emit completed spans
retroactively against the context captured at submit time.
"""

from __future__ import annotations

import contextvars
import os
import random
import re
import threading
import time
from typing import Any, Dict, Optional

from . import flags as _flags
from . import telemetry

_ID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")
_HEADER_RE = re.compile(r"^([A-Za-z0-9_.-]{1,64})-([0-9a-f]{16})$")


class SpanContext:
    """Identity of one sampled span: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def header(self) -> str:
        """Wire form for cross-process propagation (inject/extract)."""
        return f"{self.trace_id}-{self.span_id}"

    def __repr__(self):
        return f"SpanContext(trace={self.trace_id}, span={self.span_id})"


_ctx: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("pt_trace_ctx", default=None)
_rng = random.Random()   # urandom-seeded; ids need uniqueness, not secrecy

# recently-active traces — (finish ts, trace_id) per finished span, so
# the incident pipeline (core/incidents.py) can name the trace ids that
# were live around a trip point and tools/incident_report.py can pull
# their spans out of the flight-recorder ring. Plain lock + bounded
# deque: a few ns per finished SAMPLED span, nothing when tracing is off.
_recent_lock = threading.Lock()
_recent_traces: "deque" = None  # type: ignore[assignment]


def _note_trace(trace_id: str):
    global _recent_traces
    with _recent_lock:
        if _recent_traces is None:
            from collections import deque

            _recent_traces = deque(maxlen=256)
        _recent_traces.append((time.time(), trace_id))


def recent_trace_ids(window_s: float = 120.0,
                     now: Optional[float] = None) -> list:
    """Unique trace ids whose spans finished within the last
    ``window_s`` seconds, newest first — the "active traces" an
    incident dump correlates its ring spans against."""
    if now is None:
        now = time.time()
    cut = now - max(window_s, 0.0)
    with _recent_lock:
        items = list(_recent_traces) if _recent_traces is not None else []
    out, seen = [], set()
    for ts, tid in reversed(items):
        if ts >= cut and tid not in seen:
            seen.add(tid)
            out.append(tid)
    return out


def _new_id() -> str:
    return f"{_rng.getrandbits(64):016x}"


def _clean_trace_id(raw: str) -> str:
    """An externally supplied trace id (X-Request-Id) must be safe to
    embed in JSONL/headers/filenames; anything odd maps deterministically
    to a hex digest so correlation still works."""
    raw = str(raw).strip()
    if _ID_RE.match(raw):
        return raw
    import hashlib

    return hashlib.md5(raw.encode("utf-8", "replace")).hexdigest()[:16]


class _NullSpan:
    """Shared no-op context manager — the entire cost of tracing-off."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


_NULL = _NullSpan()


class _Span:
    """An open sampled span; emits its record on __exit__."""

    __slots__ = ("name", "ctx", "parent_id", "attrs", "_token", "_start",
                 "_t0")

    def __init__(self, name: str, ctx: SpanContext,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.attrs = attrs

    def __enter__(self) -> SpanContext:
        self._token = _ctx.set(self.ctx)
        self._start = time.time()
        self._t0 = time.perf_counter()
        return self.ctx

    def __exit__(self, et, ev, tb):
        _ctx.reset(self._token)
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        attrs = {"trace": self.ctx.trace_id, "span": self.ctx.span_id,
                 "parent": self.parent_id,
                 "start": round(self._start, 6),
                 "pid": os.getpid(),
                 "tid": threading.current_thread().name}
        if self.attrs:
            attrs.update(self.attrs)
        if et is not None:
            attrs["error"] = et.__name__
        telemetry.counter_quiet("trace.spans")
        _note_trace(self.ctx.trace_id)
        telemetry.event("span", self.name, round(dur_ms, 4), attrs)
        return False


def _sampled_root() -> bool:
    rate = _flags.flag("trace_sample_rate")
    if rate <= 0.0:
        return False
    return rate >= 1.0 or _rng.random() < rate


# -- the public surface ------------------------------------------------------

def tracing() -> bool:
    """True when spans opened NOW would be recorded (inside a sampled
    trace, or a nonzero sample rate may start one)."""
    return _ctx.get() is not None or _flags.flag("trace_sample_rate") > 0.0


def current() -> Optional[SpanContext]:
    """The active sampled span context of this thread/task, if any."""
    return _ctx.get()


def span(name: str, **attrs):
    """Open a span. Inside an active trace: a child. Outside: a root,
    subject to FLAGS_trace_sample_rate — unsampled/off returns a shared
    no-op context manager whose __enter__ yields None."""
    parent = _ctx.get()
    if parent is None:
        if not _sampled_root():
            return _NULL
        return _Span(name, SpanContext(_new_id(), _new_id()), None, attrs)
    return _Span(name, SpanContext(parent.trace_id, _new_id()),
                 parent.span_id, attrs)


def root_span(name: str, trace_id: Optional[str] = None,
              force: bool = False, **attrs):
    """Start a NEW trace (ignores any active context). ``trace_id`` pins
    the id (an X-Request-Id-style external correlation key) and
    ``force=True`` bypasses sampling — a caller who names their request
    wants it traced."""
    if not force and not _sampled_root():
        return _NULL
    tid = _clean_trace_id(trace_id) if trace_id else _new_id()
    return _Span(name, SpanContext(tid, _new_id()), None, attrs)


def inject() -> Optional[str]:
    """Serialise the current context for the wire ('' semantics: None
    when no sampled trace is active — callers send nothing)."""
    c = _ctx.get()
    return c.header() if c is not None else None


def extract(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a propagated '<trace>-<span>' header; None on absent or
    malformed input (a bad header must never fail the carrying RPC)."""
    if not header:
        return None
    m = _HEADER_RE.match(str(header).strip())
    if not m:
        return None
    return SpanContext(m.group(1), m.group(2))


def span_from(header: Optional[str], name: str, **attrs):
    """Open a span continuing a REMOTE parent. A valid header is always
    honoured regardless of the local sample rate (the origin sampled);
    an absent/invalid header degrades to a plain local ``span()``."""
    parent = extract(header)
    if parent is None:
        return span(name, **attrs)
    return _Span(name, SpanContext(parent.trace_id, _new_id()),
                 parent.span_id, attrs)


def record(name: str, parent: Optional[SpanContext],
           start_s: float, end_s: float, **attrs) -> Optional[SpanContext]:
    """Emit a COMPLETED span retroactively under ``parent`` (a context
    captured earlier, possibly on another thread — the serving engine's
    batch worker reconstructing a request's queue-wait/batch/predictor
    timeline). Returns the new span's context so callers can parent
    further spans under it; no-op (None) without a parent."""
    if parent is None:
        return None
    ctx = SpanContext(parent.trace_id, _new_id())
    rec_attrs = {"trace": ctx.trace_id, "span": ctx.span_id,
                 "parent": parent.span_id, "start": round(start_s, 6),
                 "pid": os.getpid(),
                 "tid": threading.current_thread().name}
    if attrs:
        rec_attrs.update(attrs)
    telemetry.counter_quiet("trace.spans")
    _note_trace(ctx.trace_id)
    telemetry.event("span", name, round((end_s - start_s) * 1e3, 4),
                    rec_attrs)
    return ctx
